"""Sharded watch fan-out: the server-owned subscription table.

The emitter dispatch this replaces made every accepted connection
register four store listeners and filter every change event against its
own watch dicts — one ``dataChanged`` cost O(all connections) Python
callbacks even when a single connection watched the path, and each
subscriber's notification was its own plane write.  At fleet scale that
is the serving plane's whole budget: the ROADMAP's million-session box
cannot spend a callback per connection per mutation.

The :class:`WatchTable` inverts the index.  One listener per store
event consults ``(kind, path) → subscriber set`` — O(watchers-on-path),
not O(connections) — encodes the notification once per distinct
``(type, path, zxid)`` within the tick (a per-tick memo, so interleaved
event kinds cannot thrash a depth-1 cache), and buffers the shared
bytes per subscriber.  Connections are assigned round-robin to K
shards; each shard schedules ONE flush callback per busy tick and
drains its dirty connections' notification batches as one joined
``SendPlane.send`` per connection — the PR 4 per-connection cork
generalized to per-shard scheduling, so a 100k-watcher event costs K
``call_soon``s instead of 100k, and every connection's notifications of
the tick leave in one segment (further coalesced with its replies by
the existing plane, durability barrier included).

Ordering contract (identical to the emitter path per connection):

- notifications append in store-event order;
- a reply sent after a notification was buffered drains the buffer
  first (``ServerConnection._write_bytes``), so the wire never shows a
  later reply overtaking an earlier notification — the ZooKeeper
  guarantee that a client sees the watch event before any read result
  reflecting the new state;
- fault injection stays a per-frame boundary BEFORE the shard cork
  (same rule as the send plane's): an injected delivery pre-flushes
  the connection's buffered notifications and its plane, so a faulted
  frame cannot reorder.

``ZKSTREAM_NO_WATCHTABLE=1`` (or ``ZKServer(watchtable=False)``)
disables the table and falls back to the per-connection emitter path —
the validator tier, exactly like the codec and cork kill switches; the
parity suite (tests/test_watchtable.py) holds the two paths to
identical notification streams.

Observability: per-shard flush batches land in the shared
``zookeeper_flush_batch_frames`` / ``_bytes`` histograms labelled
``plane="fanout"``; shard-flush duration in ``zk_fanout_tick_ms``.
Both are scraped by ``bench.py --fanout`` (`make bench-fanout`).

Beneath the shard cork sits the batched-syscall transport tier
(io/transport.py): each dirty connection's ``send_flush`` defers its
joined batch to the server's shared submission queue, so a wide
fan-out tick leaves in ONE io_uring submission (or one C writev
batch) covering every shard's connections instead of one
``transport.write`` per subscriber — the ordering and durability
contracts above are enforced by the send plane identically on every
backend.
"""

from __future__ import annotations

import os
import time

from ..io.sendplane import (
    BYTE_BUCKETS,
    FRAME_BUCKETS,
    METRIC_FLUSH_BYTES,
    METRIC_FLUSH_FRAMES,
)
from ..protocol.consts import XID_NOTIFICATION
from ..utils.aio import ambient_loop

METRIC_FANOUT_TICK = 'zk_fanout_tick_ms'

#: Shard-flush duration buckets (ms): the interesting band is whether
#: a 100k-subscriber event amortizes to sub-millisecond per shard.
TICK_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                25.0, 50.0, 100.0)

#: Default shard count (``ZKSTREAM_FANOUT_SHARDS`` overrides): enough
#: to keep one shard's dirty set small under a wide fan-out, few
#: enough that an idle tick schedules almost nothing.
DEFAULT_SHARDS = 8

#: Per-tick encode-memo cap: distinct (type, path, zxid) events per
#: tick is normally tiny (one mutation emits at most two), but a
#: pathological tick must not grow the memo without bound.
MEMO_CAP = 256


def watchtable_default() -> bool:
    """Process-wide default for new servers (env kill switch)."""
    return os.environ.get('ZKSTREAM_NO_WATCHTABLE') != '1'


def shard_count_default() -> int:
    try:
        n = int(os.environ.get('ZKSTREAM_FANOUT_SHARDS', ''))
    except ValueError:
        return DEFAULT_SHARDS
    return n if n > 0 else DEFAULT_SHARDS


class _Shard:
    """One shard's per-tick state: the dirty connection list and
    whether its flush callback is already scheduled this tick."""

    __slots__ = ('dirty', 'scheduled')

    def __init__(self) -> None:
        self.dirty: list = []
        self.scheduled = False


class WatchTable:
    """One member's reverse watch index + sharded notification cork.

    Owned by :class:`~.server.ZKServer`; subscribes ONCE to the
    member's store (watch locality: a watch armed through a lagging
    follower fires when THAT member applies the transaction, exactly
    as the per-connection emitter path did).
    """

    def __init__(self, server, shards: int | None = None,
                 collector=None):
        self.server = server
        self.nshards = shards if shards else shard_count_default()
        self._shards = [_Shard() for _ in range(self.nshards)]
        self._rr = 0
        #: The reverse index: path -> set of ServerConnection, one map
        #: per watch kind.  Invariant: ``conn`` is in
        #: ``data_index[p]`` iff ``p`` is in ``conn.data_watches``
        #: (same for child), so close-time cleanup is O(paths the
        #: connection watched).
        self.data_index: dict[str, set] = {}
        self.child_index: dict[str, set] = {}
        #: Persistent-watch indexes (ADD_WATCH, opcode 106): exact
        #: node subscribers and subtree-root subscribers.  Unlike the
        #: one-shot indexes above these SURVIVE fires — a store event
        #: consults them without popping, and a recursive entry
        #: matches every descendant by ancestor-prefix walk
        #: (O(path depth) dict hits per event, only when any
        #: persistent watch exists at all).
        self.persistent_index: dict[str, set] = {}
        self.recursive_index: dict[str, set] = {}
        #: Maintained armed-watch count across this member's
        #: connections — what ``mntr``'s ``zk_watch_count`` scrapes,
        #: O(1) instead of summing every connection's dicts.
        self.count = 0
        #: Persistent registration counts (mntr
        #: ``zk_persistent_watches`` / ``zk_recursive_watches``).
        self.persistent_count = 0
        self.recursive_count = 0
        #: Per-tick encode memo: (type, path, zxid) -> wire bytes.
        #: Cleared at the next tick boundary, so interleaved event
        #: kinds within one tick (a DELETED fanning to both data and
        #: child subscribers) share one encode without thrashing.
        self._memo: dict[tuple, bytes] = {}
        self._memo_scheduled = False
        self._frames_hist = None
        self._bytes_hist = None
        self._tick_hist = None
        if collector is not None:
            self._frames_hist = collector.histogram(
                METRIC_FLUSH_FRAMES,
                'Frames per coalesced transport write, by plane',
                buckets=FRAME_BUCKETS)
            self._bytes_hist = collector.histogram(
                METRIC_FLUSH_BYTES,
                'Bytes per coalesced transport write, by plane',
                buckets=BYTE_BUCKETS)
            self._tick_hist = collector.histogram(
                METRIC_FANOUT_TICK,
                'Per-shard fan-out flush duration (ms)',
                buckets=TICK_BUCKETS)
        self._store = server.store
        self._bind_store(self._store)

    def _bind_store(self, store) -> None:
        store.on('created', self._on_created)
        store.on('deleted', self._on_deleted)
        store.on('dataChanged', self._on_data_changed)
        store.on('childrenChanged', self._on_children_changed)

    def rebind_store(self, store) -> None:
        """Follow the server onto a new backing store (leadership
        failover repoints a member's db/store — server/election.py).
        The caller has already closed every connection, so the index
        is empty; only the event subscription moves."""
        old = self._store
        old.remove_listener('created', self._on_created)
        old.remove_listener('deleted', self._on_deleted)
        old.remove_listener('dataChanged', self._on_data_changed)
        old.remove_listener('childrenChanged',
                            self._on_children_changed)
        self._store = store
        self._bind_store(store)

    # -- connection membership --

    def add_conn(self, conn) -> None:
        """Assign a freshly-handshaken connection to a shard.  A
        connection accepted through the sharded ingress plane keeps
        its ACCEPT shard as its fan-out shard (io/ingress.py: the
        affinity key — arms, fan-out buffer and send-plane cork all
        live with the shard that drains the connection); validator-
        path connections round-robin as before (deterministic and
        balanced)."""
        shard = getattr(conn, '_ingress_shard', None)
        if shard is not None:
            conn._fanout_shard = shard % self.nshards
            return
        conn._fanout_shard = self._rr % self.nshards
        self._rr += 1

    def remove_conn(self, conn) -> None:
        """Connection closed: drop its index entries (O(paths it
        watched)) and its buffered notifications — the bytes have
        nowhere to go.  The caller has already flushed anything that
        should beat the FIN."""
        for path in conn.data_watches:
            subs = self.data_index.get(path)
            if subs is not None:
                subs.discard(conn)
                if not subs:
                    del self.data_index[path]
                self.count -= 1
        for path in conn.child_watches:
            subs = self.child_index.get(path)
            if subs is not None:
                subs.discard(conn)
                if not subs:
                    del self.child_index[path]
                self.count -= 1
        for path, recursive in conn.persistent_watches.items():
            idx = (self.recursive_index if recursive
                   else self.persistent_index)
            subs = idx.get(path)
            if subs is not None:
                subs.discard(conn)
                if not subs:
                    del idx[path]
                if recursive:
                    self.recursive_count -= 1
                else:
                    self.persistent_count -= 1
        conn.data_watches.clear()
        conn.child_watches.clear()
        conn.persistent_watches.clear()
        conn._fanout_buf.clear()

    # -- arming / disarming (the connection's watch helpers call in) --

    def arm(self, kind: str, path: str, conn) -> None:
        """Register one one-shot watch; the caller guarantees it is
        not already armed (the connection dict is the dedup)."""
        idx = self.data_index if kind == 'data' else self.child_index
        subs = idx.get(path)
        if subs is None:
            idx[path] = subs = set()
        subs.add(conn)
        self.count += 1

    def disarm(self, kind: str, path: str, conn) -> None:
        """Unregister a watch the connection consumed out of band
        (SET_WATCHES catch-up resolving a stale arm)."""
        idx = self.data_index if kind == 'data' else self.child_index
        subs = idx.get(path)
        if subs is not None and conn in subs:
            subs.discard(conn)
            if not subs:
                del idx[path]
            self.count -= 1

    def arm_persistent(self, path: str, conn,
                       recursive: bool) -> None:
        """Register one persistent (ADD_WATCH) subscription; the
        caller guarantees it is not already armed under this mode
        (``conn.persistent_watches`` is the dedup)."""
        idx = self.recursive_index if recursive \
            else self.persistent_index
        subs = idx.get(path)
        if subs is None:
            idx[path] = subs = set()
        subs.add(conn)
        if recursive:
            self.recursive_count += 1
        else:
            self.persistent_count += 1

    def disarm_persistent(self, path: str, conn,
                          recursive: bool) -> None:
        idx = self.recursive_index if recursive \
            else self.persistent_index
        subs = idx.get(path)
        if subs is not None and conn in subs:
            subs.discard(conn)
            if not subs:
                del idx[path]
            if recursive:
                self.recursive_count -= 1
            else:
                self.persistent_count -= 1

    # -- store event handlers (the O(watchers-on-path) hot path) --

    def _on_created(self, path: str, zxid: int) -> None:
        subs = self.data_index.pop(path, None)
        if subs:
            self._fan('CREATED', path, zxid, subs, 'data')
        if self.persistent_count or self.recursive_count:
            self._fan_persistent('CREATED', path, zxid)

    def _on_deleted(self, path: str, zxid: int) -> None:
        # a connection holding both watch kinds on the path receives
        # two DELETED frames, data-kind first — emitter-path parity
        subs = self.data_index.pop(path, None)
        if subs:
            self._fan('DELETED', path, zxid, subs, 'data')
        subs = self.child_index.pop(path, None)
        if subs:
            self._fan('DELETED', path, zxid, subs, 'child')
        if self.persistent_count or self.recursive_count:
            self._fan_persistent('DELETED', path, zxid)

    def _on_data_changed(self, path: str, zxid: int) -> None:
        subs = self.data_index.pop(path, None)
        if subs:
            self._fan('DATA_CHANGED', path, zxid, subs, 'data')
        if self.persistent_count or self.recursive_count:
            self._fan_persistent('DATA_CHANGED', path, zxid)

    def _on_children_changed(self, path: str, zxid: int) -> None:
        subs = self.child_index.pop(path, None)
        if subs:
            self._fan('CHILDREN_CHANGED', path, zxid, subs, 'child')
        if self.persistent_count:
            # exact-node persistent subscribers only: a recursive
            # subscriber sees the child's own CREATED/DELETED instead
            # (upstream PERSISTENT_RECURSIVE semantics)
            self._fan_persistent('CHILDREN_CHANGED', path, zxid,
                                 exact_only=True)

    def _persistent_subs(self, path: str,
                         exact_only: bool = False) -> set | None:
        """The persistent subscriber set for one store event: exact
        subscribers on ``path`` plus — unless ``exact_only`` — every
        recursive subscriber on ``path`` or an ancestor.  A
        connection holding both registrations gets ONE frame."""
        subs = None
        exact = self.persistent_index.get(path)
        if exact:
            subs = set(exact)
        if not exact_only and self.recursive_count:
            p = path
            ridx = self.recursive_index
            while True:
                r = ridx.get(p)
                if r:
                    subs = (subs | r) if subs else set(r)
                if len(p) <= 1:
                    break
                i = p.rfind('/')
                p = p[:i] if i > 0 else '/'
        return subs

    def _fan_persistent(self, ntype: str, path: str, zxid: int,
                        exact_only: bool = False) -> None:
        """Fan one store event to persistent subscribers.  Unlike
        :meth:`_fan` nothing is consumed — the registrations survive
        the fire — and the overload plane's soft-watermark gate is
        the EVICTING variant: a persistent subscriber never gets a
        silent notification gap (a dropped invalidation would wedge
        a watch-backed client cache stale forever), it gets a typed
        eviction and re-syncs on reconnect."""
        subs = self._persistent_subs(path, exact_only)
        if not subs:
            return
        data = self.encode(ntype, path, zxid)
        srv = self.server
        trace = getattr(srv, 'trace', None)
        if trace is not None:
            trace.note('FANOUT', path, zxid=zxid, kind='server',
                       batch=len(subs),
                       nbytes=len(data) * len(subs),
                       detail='PERSISTENT:' + ntype)
        if srv.faults is not None:
            # injection boundary: per frame, BEFORE the shard cork
            for conn in subs:
                if not conn.closed:
                    self._enqueue_persistent(conn, data)
            return
        srv.packets_sent += len(subs)
        shards = self._shards
        sched: list = []
        ov = getattr(srv, 'overload', None)
        for conn in subs:
            if conn.closed:
                srv.packets_sent -= 1
                continue
            if ov is not None \
                    and not ov.allow_persistent_notification(conn):
                # the gate EVICTED the stalled subscriber (typed
                # close) rather than dropping the frame
                srv.packets_sent -= 1
                continue
            buf = conn._fanout_buf
            if not buf:
                shard = shards[conn._fanout_shard]
                shard.dirty.append(conn)
                if not shard.scheduled:
                    shard.scheduled = True
                    sched.append(shard)
            buf.append(data)
        if sched:
            self._schedule_shards(sched)

    def _enqueue_persistent(self, conn, data: bytes) -> None:
        """The fault-injection-path twin of :meth:`_enqueue` with the
        persistent overload contract (evict, never silently drop)."""
        ov = getattr(self.server, 'overload', None)
        if ov is not None \
                and not ov.allow_persistent_notification(conn):
            return
        self.server.packets_sent += 1
        fi = self.server.faults
        if fi is not None and fi.server_tx(conn, data,
                                           pre=conn._preflush_fanout):
            return
        buf = conn._fanout_buf
        if not buf:
            shard = self._shards[conn._fanout_shard]
            shard.dirty.append(conn)
            if not shard.scheduled:
                shard.scheduled = True
                self._schedule_shards([shard])
        buf.append(data)

    def _fan(self, ntype: str, path: str, zxid: int, subs: set,
             kind: str) -> None:
        data = self.encode(ntype, path, zxid)
        self.count -= len(subs)
        srv = self.server
        trace = getattr(srv, 'trace', None)   # stub-server tolerant
        if trace is not None:
            # the fan-out leg of the zxid span chain: ONE span per
            # store event, stamped with the watch count and the wire
            # bytes it flushes (len(subs) subscribers x one shared
            # encode)
            trace.note('FANOUT', path, zxid=zxid, kind='server',
                       batch=len(subs),
                       nbytes=len(data) * len(subs),
                       detail=ntype)
        if srv.faults is not None:
            # injection boundary: per frame, BEFORE the shard cork
            for conn in subs:
                (conn.data_watches if kind == 'data'
                 else conn.child_watches).pop(path, None)
                if not conn.closed:
                    self._enqueue(conn, data)
            return
        # fault-free hot loop (the 100k-subscriber path): one-shot
        # consume + buffer, with the shard scheduling and the
        # packets_sent accounting hoisted out (closed subscribers
        # compensate — they consume the arm but send nothing)
        srv.packets_sent += len(subs)
        shards = self._shards
        sched: list = []
        ov = getattr(srv, 'overload', None)
        if kind == 'data':
            for conn in subs:
                conn.data_watches.pop(path, None)
                if conn.closed:
                    srv.packets_sent -= 1
                    continue
                if ov is not None \
                        and not ov.allow_notification(conn):
                    # soft tx watermark (io/overload.py): a stalled
                    # subscriber loses the frame — the legally lossy
                    # channel — instead of bloating the member
                    srv.packets_sent -= 1
                    continue
                buf = conn._fanout_buf
                if not buf:
                    shard = shards[conn._fanout_shard]
                    shard.dirty.append(conn)
                    if not shard.scheduled:
                        shard.scheduled = True
                        sched.append(shard)
                buf.append(data)
        else:
            for conn in subs:
                conn.child_watches.pop(path, None)
                if conn.closed:
                    srv.packets_sent -= 1
                    continue
                if ov is not None \
                        and not ov.allow_notification(conn):
                    srv.packets_sent -= 1
                    continue
                buf = conn._fanout_buf
                if not buf:
                    shard = shards[conn._fanout_shard]
                    shard.dirty.append(conn)
                    if not shard.scheduled:
                        shard.scheduled = True
                        sched.append(shard)
                buf.append(data)
        if sched:
            self._schedule_shards(sched)

    def _schedule_shards(self, shards: list) -> None:
        """Schedule shard flushes for the tick boundary.  With a
        batched transport tier the flush runs inside the tier's one
        tick callback, BEFORE its submission — so a wide fan-out's
        bytes ride the same batched syscall chain as the tick's
        replies instead of trailing it by a loop hop (or fragmenting
        into per-shard submissions)."""
        tier = getattr(self.server, 'transport_tier', None)
        if tier is not None:
            for shard in shards:
                tier.schedule_call(
                    lambda s=shard: self._flush_shard(s))
            return
        loop = ambient_loop()
        for shard in shards:
            loop.call_soon(self._flush_shard, shard)

    # -- notification encode (per-tick memo) --

    def encode(self, ntype: str, path: str, zxid: int) -> bytes:
        """Encode one notification through the server-owned codec,
        memoized per tick — shared bytes for every subscriber, and for
        the direct ``notify`` path (SET_WATCHES catch-up) too."""
        key = (ntype, path, zxid)
        data = self._memo.get(key)
        if data is None:
            data = self.server._notif_codec.encode(
                {'xid': XID_NOTIFICATION, 'zxid': zxid, 'err': 'OK',
                 'opcode': 'NOTIFICATION', 'type': ntype,
                 'state': 'SYNC_CONNECTED', 'path': path})
            if len(self._memo) >= MEMO_CAP:
                self._memo.clear()
            self._memo[key] = data
            if not self._memo_scheduled:
                self._memo_scheduled = True
                ambient_loop().call_soon(self._clear_memo)
        return data

    def _clear_memo(self) -> None:
        self._memo_scheduled = False
        self._memo.clear()

    # -- the shard cork --

    def _enqueue(self, conn, data: bytes) -> None:
        """Buffer one (already encoded, shared) notification for one
        subscriber; the shard flushes at the tick boundary.  Fault
        injection happens HERE — before the cork, per frame, with a
        pre-flush of everything the connection already has corked —
        the same boundary rule the send plane uses."""
        ov = getattr(self.server, 'overload', None)
        if ov is not None and not ov.allow_notification(conn):
            return
        self.server.packets_sent += 1
        fi = self.server.faults
        if fi is not None and fi.server_tx(conn, data,
                                           pre=conn._preflush_fanout):
            return   # the injector took over delivery (split/delay/RST)
        buf = conn._fanout_buf
        if not buf:
            shard = self._shards[conn._fanout_shard]
            shard.dirty.append(conn)
            if not shard.scheduled:
                shard.scheduled = True
                self._schedule_shards([shard])
        buf.append(data)

    def _flush_shard(self, shard: _Shard) -> None:
        """One shard's tick flush: every dirty connection's buffered
        notifications leave as one joined ``SendPlane.send``, and the
        plane is flushed on the spot — this callback IS the tick
        boundary for its connections, so letting the plane schedule
        its own per-connection flush would only add one loop-callback
        round trip per subscriber (the dominant cost at 100k
        watchers).  Replies the plane already corked this tick leave
        in the same buffer, order preserved, durability barrier
        honored (``flush_now`` gates on it)."""
        shard.scheduled = False
        dirty, shard.dirty = shard.dirty, []
        ledger = getattr(self.server, 'ledger', None)
        if ledger is not None:
            # fanout_flush tick phase: the shard loop's own time (the
            # nested send-plane writes account under cork_flush)
            ledger.enter('fanout_flush')
        t0 = time.perf_counter()
        frames = 0
        nbytes = 0
        ov = getattr(self.server, 'overload', None)
        try:
            for conn in dirty:
                buf = conn._fanout_buf
                if not buf:
                    continue
                data = buf[0] if len(buf) == 1 else b''.join(buf)
                frames += len(buf)
                # the list object is reused across ticks (cleared in
                # place): a 100k-subscriber flush must not allocate a
                # fresh buffer per connection per event
                buf.clear()
                if conn.closed:
                    continue
                nbytes += len(data)
                conn._tx.send_flush(data)
                if ov is not None:
                    # the flush is the fan-out's per-conn-per-tick
                    # boundary: a subscriber whose backlog outgrew
                    # the hard watermark is evicted right here
                    ov.check_tx(conn)
        finally:
            if ledger is not None:
                ledger.exit()
        if frames and self._frames_hist is not None:
            labels = {'plane': 'fanout'}
            self._frames_hist.observe(frames, labels)
            self._bytes_hist.observe(nbytes, labels)
            self._tick_hist.observe(
                (time.perf_counter() - t0) * 1000.0, labels)
