"""Quorum leader election over recovered (epoch, zxid) pairs.

Until this module the ensemble's leader was statically assigned:
``ZKEnsemble`` hard-wired member 0, and the OS-process tier spawned a
process whose *role* was leader — killing it killed the quorum.  The
durability plane (server/persist.py) gave every member a disk worth
trusting; this module builds the coordination layer on top of it, the
ZAB shape: when the leader is lost, members vote with the newest
``(epoch, zxid)`` pair they hold — recovered from their own WAL when
the whole ensemble died — and the highest pair wins (member id breaks
exact ties, deterministically, so a split vote cannot live-lock).
The winner bumps the **epoch**, a first-class fencing token:

- persisted as a WAL *control* record before the new leader serves a
  single write (recovered by server/persist.py on restart);
- stamped on every replication push and forwarded-write ack
  (server/replication.py): followers reject pushes from a lower
  epoch, and a deposed leader's forwarded writes bounce with a typed
  ``EPOCH_FENCED`` error instead of being silently applied;
- strictly increasing across elections — invariant 7
  (io/invariants.py) checks at-most-one-leader-per-epoch and epoch
  monotonicity over the campaign history.

Two tiers, one vote rule:

- **In-process** (:class:`ElectionCoordinator`): the members of a
  ``ZKEnsemble`` share one database, so an election is role + fencing
  bookkeeping — but the *detection* is honest: a monitor probes the
  leader's listener on a jittered backoff (io/backoff.py) and elects
  among live, unpartitioned members only when a quorum of the
  membership is available; a partitioned minority can never win.
- **OS-process** (:class:`ElectionPeer` + :func:`run_member`): every
  member is a symmetric peer process with an election port.  A
  looking peer polls its peers for votes (jittered backoff between
  rounds); with a quorum reachable the highest (epoch, zxid, id)
  wins, promotes its replica mirror (or its recovered WAL) into the
  leader database, starts a ``ReplicationService``, and the rest
  re-follow it through the existing tail-resync / snapshot-bootstrap
  machinery.  Leader loss is the push-channel EOF
  (``RemoteLeader.on_leader_lost``).  No operator anywhere.

``ZKSTREAM_NO_ELECTION=1`` (or ``ZKEnsemble(election=False)``) keeps
the static-leader behavior as an env-gated validator, the same knob
pattern as the watch-table emitter path.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import os
import socket
import time

from ..io.backoff import BackoffPolicy
from ..utils.aio import ambient_loop
from ..utils.events import EventEmitter
from .replication import _dump, _read_msg, quorum_of

log = logging.getLogger('zkstream_tpu.server.election')

METRIC_ELECTION = 'zk_election_ms'
ELECTION_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                    500.0, 1000.0, 2500.0, 5000.0)

#: In-process leader-liveness probe cadence (ms).  Detection latency
#: is bounded by one probe interval; campaigns shrink it.
DEFAULT_HEARTBEAT_MS = 400

#: OS-process vote-round pacing: full-jittered delays between poll
#: rounds, walking up while no quorum is reachable (the storm-
#: decorrelation shape of io/backoff.py — N followers losing one
#: leader must not stampede each other's election ports).
PEER_POLICY = BackoffPolicy(timeout=1000, retries=3, delay=60,
                            cap=1000)

#: How many denied claim rounds before a candidate escalates to the
#: next epoch.  Grants are STICKY (a target epoch, once granted,
#: belongs to that candidate forever — a time-based re-grant could
#: hand the same epoch to a second live candidate whose rival is
#: merely promoting slowly), so liveness comes from escalation
#: instead: a candidate denied its target — the granted claimant died
#: mid-claim, or a slow rival holds it — claims target+1, which is a
#: fresh arbitration.  Two winners can then stand only at DIFFERENT
#: epochs, which the fencing token resolves (the lower one deposes
#: itself via the supersession watch).
CLAIM_ESCALATE_AFTER = 3

#: A standing leader's supersession-watch poll period: how often it
#: asks its peers whether a newer-epoch leader stands (the deposed-
#: while-partitioned case — it fences itself and steps down).  Also
#: the bound on how long a deposed leader can keep acking direct
#: client writes; analogous to real ZK's syncLimit window.
LEAD_WATCH_S = 0.4


def election_enabled() -> bool:
    """Global kill switch (mirrors ``ZKSTREAM_NO_WATCHTABLE``): the
    static-leader path stays available as an env-gated validator."""
    return os.environ.get('ZKSTREAM_NO_ELECTION') != '1'


@dataclasses.dataclass(frozen=True, order=True)
class Vote:
    """One member's claim in an election.  Field order IS the vote
    rule: highest epoch wins; equal epochs fall to the highest zxid
    (the member holding the most history — no acked write can be
    seeded away); an exact (epoch, zxid) tie breaks to the highest
    member id, so every voter computes the same winner from the same
    ballot and a split vote resolves in one round."""

    epoch: int
    zxid: int
    member: int


def tally(votes) -> Vote | None:
    """The election rule, shared verbatim by both tiers."""
    votes = list(votes)
    if not votes:
        return None
    return max(votes)


def _promise_path(d: str) -> str:
    return os.path.join(d, 'promise')


def read_promise(d: str) -> int:
    """The highest claim target ever granted from this directory."""
    try:
        with open(_promise_path(d)) as f:
            return int(f.read().strip() or 0)
    except (FileNotFoundError, ValueError):
        return 0


def write_promise(d: str, target: int) -> None:
    """Durably record a claim grant (write + fsync + atomic rename):
    a promise, like an accepted epoch, must survive the promiser —
    a restarted peer that forgot its grant could hand the same epoch
    to a second live candidate."""
    tmp = _promise_path(d) + '.tmp'
    with open(tmp, 'w') as f:
        f.write('%d\n' % (target,))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, _promise_path(d))


def allocate_ports(n: int, host: str = '127.0.0.1') -> list[int]:
    """Pre-allocate n distinct ephemeral ports (bind/close): peer
    processes must know each other's election ports before any of
    them exists."""
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((host, 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


# ---------------------------------------------------------------------
# In-process tier: the ZKEnsemble coordinator.
# ---------------------------------------------------------------------


class ElectionCoordinator(EventEmitter):
    """Leader election for an in-process ``ZKEnsemble``.

    The members share one ``ZKDatabase``, so promotion is role +
    fencing bookkeeping — what the election *changes* is observable
    everywhere else: the epoch bumps (WAL-logged), ``zk_member_role``
    flips, a deposed-but-alive ex-leader's writes bounce with
    ``EPOCH_FENCED`` until it heals, and the campaign history gains
    the election records invariant 7 replays.

    Events: ``elected(member, epoch, duration_ms)``,
    ``electing(reason)``.
    """

    def __init__(self, servers, db, heartbeat_ms: int | None = None,
                 seed: int | None = None, collector=None,
                 voters: int | None = None):
        super().__init__()
        self.servers = servers
        self.db = db
        #: The VOTING membership: members ``0..voters-1``.  Members
        #: past it are observers (README "Read plane") — they never
        #: enter a ballot, never win, and never count toward the
        #: election quorum denominator.
        self.voters = voters if voters is not None else len(servers)
        #: Dynamic membership (README "Dynamic membership"): the
        #: CURRENT voter set by member index — reconfig records
        #: (server/store.py) repoint it via :meth:`set_config`.  While
        #: ``old_voter_set`` stands (a joint window), an election
        #: needs a reachable majority of BOTH sets, and the ballot is
        #: open to their union; once the final record commits, a
        #: removed member can neither stand nor be counted reachable.
        self.voter_set: set[int] = set(range(self.voters))
        self.old_voter_set: set[int] | None = None
        self.heartbeat_ms = (heartbeat_ms if heartbeat_ms is not None
                             else DEFAULT_HEARTBEAT_MS)
        self.leader_idx = 0
        self.elections = 0
        #: members fenced at a stale epoch (an alive-but-deposed
        #: ex-leader): writes through them raise EPOCH_FENCED
        self.deposed: set[int] = set()
        #: members cut off from the quorum: they neither vote nor win
        self.partitioned: set[int] = set()
        self._probe_policy = BackoffPolicy(
            timeout=self.heartbeat_ms, retries=3,
            delay=self.heartbeat_ms, cap=self.heartbeat_ms * 8)
        self._seed = seed
        self._task: asyncio.Task | None = None
        self._electing = False
        self._stopping = False
        self._hist = None
        if collector is not None:
            self.bind_metrics(collector)
        for i, s in enumerate(self.servers):
            if i < self.voters:
                s.role = ('leader' if i == self.leader_idx
                          else 'follower')
            s.elections_ref = self
            s.fence = (lambda idx=i: idx in self.deposed)

    def bind_metrics(self, collector) -> None:
        self._hist = collector.histogram(
            METRIC_ELECTION,
            'Leader-loss detection to new-leader promotion, ms',
            buckets=ELECTION_BUCKETS)

    # -- liveness --

    def _alive(self, idx: int) -> bool:
        return self.servers[idx].listening

    def leader_alive(self) -> bool:
        return self._alive(self.leader_idx) \
            and self.leader_idx not in self.partitioned

    def start(self) -> None:
        if self._task is None:
            self._stopping = False
            self._task = ambient_loop().create_task(self._monitor())

    def stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _monitor(self) -> None:
        """Probe the leader on a jittered cadence; on loss, elect.
        The backoff only *grows* while no election can complete (no
        quorum of live members) — a genuinely-down ensemble is probed
        ever more gently — and resets the moment a leader stands."""
        backoff = self._probe_policy.backoff(self._seed)
        try:
            while not self._stopping:
                if self.leader_alive():
                    backoff.reset()
                    delay = backoff.next_delay()
                else:
                    won = await self.elect('heartbeat-timeout')
                    if won is not None:
                        backoff.reset()
                    delay = backoff.next_delay()
                await asyncio.sleep(
                    (self.heartbeat_ms * 0.25 + delay * 0.75) / 1000.0)
        except asyncio.CancelledError:
            pass

    # -- the election itself --

    def set_config(self, voter_set, old_voter_set=None) -> None:
        """Adopt a reconfig record's voter set(s): ``voter_set`` is
        C_new, ``old_voter_set`` C_old while the joint window stands
        (both-majorities rule).  A member removed by the final record
        leaves the ballot immediately."""
        self.voter_set = set(voter_set)
        self.old_voter_set = (set(old_voter_set)
                              if old_voter_set is not None else None)

    def _candidates(self) -> list[int]:
        # the live ballot: current voters, plus C_old's during a
        # joint window; an observer (or a removed member) holds the
        # same history but must never stand (or be counted reachable)
        live = self.voter_set | (self.old_voter_set or set())
        return [i for i in sorted(live)
                if i < len(self.servers) and self._alive(i)
                and i not in self.partitioned]

    def _quorum_reached(self, cands) -> bool:
        """A reachable majority of EVERY active voter set: C_new
        alone in stable state, C_old AND C_new during a joint
        window — the election half of joint consensus."""
        cs = set(cands)
        for cfg in ((self.voter_set,) if self.old_voter_set is None
                    else (self.voter_set, self.old_voter_set)):
            if not cfg or len(cs & cfg) < quorum_of(len(cfg)):
                return False
        return True

    async def elect(self, reason: str) -> int | None:
        """Run one election among live, unpartitioned members.
        Returns the winning member index, or None when no quorum of
        the total membership is reachable (a partitioned minority —
        or a mostly-dead ensemble — must NOT seed a new epoch)."""
        if self._electing or self._stopping:
            return None
        self._electing = True
        t0 = time.perf_counter()
        try:
            cands = self._candidates()
            if not self._quorum_reached(cands):
                return None
            self.emit('electing', reason)
            for i in cands:
                self.servers[i].role = 'electing'
            # one cooperative yield: role flips are observable (mntr
            # scrapes a member mid-election as 'electing'), and a
            # kill racing the vote lands before the tally
            await asyncio.sleep(0)
            cands = self._candidates()
            if not self._quorum_reached(cands):
                for i in self._candidates():
                    self.servers[i].role = 'follower'
                return None
            votes = [Vote(epoch=self.db.epoch,
                          zxid=self.servers[i].store.zxid, member=i)
                     for i in cands]
            win = tally(votes)
            new_epoch = self.db.epoch + 1
            self.db.bump_epoch(new_epoch)
            old = self.leader_idx
            if old != win.member and self._alive(old):
                # an ex-leader that survived its own deposition (a
                # healed partition brings it back): fence it until it
                # rejoins the current epoch
                self.deposed.add(old)
            self.deposed.discard(win.member)
            srv = self.servers[win.member]
            srv.store.catch_up()
            for i in cands:
                self.servers[i].role = \
                    'leader' if i == win.member else 'follower'
            self.leader_idx = win.member
            self.elections += 1
            dur_ms = (time.perf_counter() - t0) * 1000.0
            if self._hist is not None:
                self._hist.observe(dur_ms)
            if srv.trace is not None:
                srv.trace.note('ELECTION', kind='server',
                               batch=len(votes), detail=reason,
                               duration_ms=round(dur_ms, 3))
                srv.trace.note('EPOCH_BUMP', zxid=self.db.zxid,
                               kind='server',
                               detail='epoch=%d' % (new_epoch,))
            log.info('member %d elected leader at epoch %d (%s, '
                     '%d votes, %.1f ms)', win.member, new_epoch,
                     reason, len(votes), dur_ms)
            self.emit('elected', win.member, new_epoch, dur_ms)
            return win.member
        finally:
            self._electing = False

    # -- membership edges the ensemble reports --

    def note_restart(self, idx: int) -> None:
        """A killed member is back: it rejoins at the current epoch as
        a follower (never as the leader it may once have been)."""
        self.deposed.discard(idx)
        if idx != self.leader_idx:
            self.servers[idx].role = 'follower'

    def partition(self, idx: int) -> None:
        self.partitioned.add(idx)

    def heal(self, idx: int | None = None) -> None:
        """Heal a partition: the member observes the current epoch
        and rejoins as a follower — its fence lifts."""
        idxs = list(self.partitioned) if idx is None else [idx]
        for i in idxs:
            self.partitioned.discard(i)
            self.deposed.discard(i)
            if i != self.leader_idx and self._alive(i):
                self.servers[i].role = 'follower'


# ---------------------------------------------------------------------
# OS-process tier: symmetric peer processes.
# ---------------------------------------------------------------------


class ElectionPeer:
    """One member process's election endpoint + vote loop.

    The peer answers ``vote?`` probes with its live state (looking /
    following / leading, epoch, zxid, and — when leading — its
    replication port), and :meth:`resolve` runs the looking-side loop:
    poll every peer, follow a standing leader at ``>=`` our epoch,
    else — with a quorum reachable — compute the winner all reachable
    peers will also compute.  A minority partition never reaches
    quorum and so never seeds an epoch."""

    def __init__(self, member_id: int, peers, total: int,
                 host: str = '127.0.0.1', port: int = 0,
                 policy: BackoffPolicy = PEER_POLICY,
                 seed: int | None = None,
                 promise_dir: str | None = None,
                 observer: bool = False):
        self.member_id = member_id
        self.peers = list(peers)          # [(id, host, election_port)]
        #: ``total`` is the VOTING membership.  An observer peer
        #: (README "Read plane") is outside it: its vote replies are
        #: stamped ``observer`` (excluded from every ballot and every
        #: reachable-quorum count), it denies every claim (a grant
        #: from outside the voter set must never help a candidate
        #: assemble a "quorum"), and :meth:`resolve` never stands —
        #: it only ever follows a leader the voters elected.
        self.observer = observer
        self.total = total
        self.host = host
        self.port = port
        self.policy = policy
        self.seed = seed
        #: durable promise floor: the highest target ever granted
        #: from this directory — consulted (and advanced, fsynced)
        #: by grant() so a SIGKILLed-and-restarted granter cannot
        #: hand an already-promised epoch to a second candidate.
        #: None = in-memory only (unit tests).
        self.promise_dir = promise_dir
        self.promised_floor = (read_promise(promise_dir)
                               if promise_dir else 0)
        self.state = 'looking'
        self.repl_port: int | None = None
        #: live-state providers, set by the owner (run_member): voting
        #: must read the CURRENT epoch/zxid, not a stale copy
        self.epoch_fn = lambda: 0
        self.zxid_fn = lambda: 0
        #: claim grants: target epoch -> candidate vote.  Each target
        #: epoch is promised to at most ONE candidate, EVER — the
        #: arbitration that keeps two candidates with overlapping
        #: (but different) reachable quorums from both seeding the
        #: SAME epoch: the overlap peer grants one of them and denies
        #: the other, so only one can reach a quorum of grants.
        #: Liveness on a wedged target (claimant died mid-claim) is
        #: the candidate's job: escalate to target+1
        #: (CLAIM_ESCALATE_AFTER).  Stale targets are pruned once an
        #: epoch at or above them stands.
        self._grants: dict[int, Vote] = {}
        self._server: asyncio.base_events.Server | None = None

    async def start(self) -> 'ElectionPeer':
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None

    def note_leading(self, repl_port: int) -> None:
        self.state = 'leading'
        self.repl_port = repl_port

    def note_following(self) -> None:
        self.state = 'following'
        self.repl_port = None

    def note_looking(self) -> None:
        self.state = 'looking'
        self.repl_port = None

    def grant(self, target: int, vote: Vote) -> bool:
        """One peer's claim arbitration: grant ``target`` to at most
        one candidate, ever (sticky — never re-granted to a rival,
        however long the claimant takes to promote), and never to a
        target at or below the epoch already standing here.  The same
        candidate re-claiming is idempotent."""
        if self.observer:
            return False              # observers never arbitrate
        epoch = self.epoch_fn()
        for t in [t for t in self._grants if t <= epoch]:
            del self._grants[t]       # settled eras: prune
        if target <= epoch:
            return False              # that era already stands
        cur = self._grants.get(target)
        if cur is None and target <= self.promised_floor:
            # promised before a restart wiped the in-memory table:
            # the original claimant may still be live — deny, and let
            # whoever is asking escalate to a fresh target.  Over-
            # denial costs a skipped epoch number, never safety.
            return False
        if cur is None or cur == vote:
            self._grants[target] = vote
            if target > self.promised_floor:
                self.promised_floor = target
                if self.promise_dir is not None:
                    write_promise(self.promise_dir, target)
            return True
        return False

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            msg = await asyncio.wait_for(_read_msg(reader), 5.0)
            if msg[0] == 'vote?':
                # an observer's reply is stamped as such: voters drop
                # it from ballots and reachable-quorum counts
                state = 'observer' if self.observer else self.state
                writer.write(_dump(
                    ('vote', self.member_id, state,
                     self.epoch_fn(), self.zxid_fn(),
                     self.repl_port)))
                await writer.drain()
            elif msg[0] == 'claim?':
                _, target, vote_t = msg
                ok = self.grant(target, Vote(*vote_t))
                writer.write(_dump(('claim', self.member_id, ok)))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.TimeoutError, TimeoutError):
            pass
        finally:
            try:
                writer.close()
            except (ConnectionError, RuntimeError):
                pass

    async def _ask(self, host: str, port: int, request: tuple,
                   reply_tag: str):
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, port), 1.0)
        except (OSError, asyncio.TimeoutError, TimeoutError):
            return None
        try:
            writer.write(_dump(request))
            await writer.drain()
            msg = await asyncio.wait_for(_read_msg(reader), 1.0)
            if msg[0] == reply_tag:
                return msg
        except (OSError, asyncio.IncompleteReadError,
                asyncio.TimeoutError, TimeoutError):
            return None
        finally:
            try:
                writer.close()
            except (ConnectionError, RuntimeError):
                pass
        return None

    async def _poll(self) -> list:
        req = ('vote?', self.member_id)
        out = await asyncio.gather(
            *(self._ask(h, p, req, 'vote')
              for _id, h, p in self.peers))
        return [m for m in out if m is not None]

    async def _claim_quorum(self, target: int, vote: Vote) -> bool:
        """The claim round: collect single-grant promises for
        ``target`` from every reachable peer (self included, same
        rule).  True only with a quorum of grants — at most one
        candidate per epoch can get there."""
        if not self.grant(target, vote):
            return False
        req = ('claim?', target,
               (vote.epoch, vote.zxid, vote.member))
        out = await asyncio.gather(
            *(self._ask(h, p, req, 'claim')
              for _id, h, p in self.peers))
        granted = 1 + sum(1 for m in out
                          if m is not None and m[2])
        return granted >= quorum_of(self.total)

    async def resolve(self):
        """Loop until this peer either leads or has a leader to
        follow.  Returns ``('lead', target_epoch)`` — the epoch this
        peer holds a quorum of claim grants for — or
        ``('follow', (leader_id, host, repl_port, leader_epoch))``."""
        self.note_looking()
        backoff = self.policy.backoff(self.seed)
        denied = 0
        escalate = 0
        while True:
            replies = await self._poll()
            my_epoch, my_zxid = self.epoch_fn(), self.zxid_fn()
            leaders = [r for r in replies
                       if r[2] == 'leading' and r[5] is not None]
            if leaders:
                best = max(leaders, key=lambda r: r[3])
                if best[3] >= my_epoch:
                    host = next(h for i, h, _p in self.peers
                                if i == best[1])
                    return ('follow', (best[1], host, best[5],
                                       best[3]))
            if self.observer:
                # never stand: keep polling until a voter-elected
                # leader answers (jittered, like a denied candidate)
                await asyncio.sleep(backoff.next_delay() / 1000.0)
                continue
            # observers are outside the ballot AND the reachable
            # count: total is the voting membership
            voter_replies = [r for r in replies
                             if r[2] != 'observer']
            if len(voter_replies) + 1 >= quorum_of(self.total):
                votes = [Vote(r[3], r[4], r[1])
                         for r in voter_replies]
                my_vote = Vote(my_epoch, my_zxid, self.member_id)
                votes.append(my_vote)
                win = tally(votes)
                if win.member == self.member_id:
                    # the claim round: winning the tally of MY
                    # reachable ballot is not enough — another
                    # candidate's reachable ballot may differ.  Only
                    # a quorum of per-epoch single grants arbitrates
                    # (the overlap peer grants one of us), so two
                    # winners can never seed the same epoch.  A
                    # persistently denied target (its claimant died
                    # mid-claim, or a slow rival holds it) is
                    # escalated — fresh arbitration at target+1; a
                    # doubly-led era can then only be a LOWER epoch,
                    # which the supersession watch fences away.
                    target = max(v.epoch for v in votes) + 1 \
                        + escalate
                    if await self._claim_quorum(target, my_vote):
                        return ('lead', target)
                    denied += 1
                    if denied >= CLAIM_ESCALATE_AFTER:
                        denied = 0
                        escalate += 1
                # else: wait for the real winner's 'leading' state
                # on a later poll
            await asyncio.sleep(backoff.next_delay() / 1000.0)


async def run_member(member_id: int, wal_dir: str, client_port: int,
                     election_port: int, peers,
                     sync: str = 'tick',
                     ready_cb=None, observer: bool = False,
                     voters: int | None = None,
                     voter_ids=None, observer_ids=None) -> None:
    """One symmetric ensemble-member process: recover local state,
    run elections forever, serve clients on ``client_port`` whatever
    the current role.  ``peers`` is ``[(id, host, election_port)]``
    for every OTHER member.  Runs until the process is killed —
    being SIGKILLed mid-role is the point of the tier.

    ``observer=True`` makes this member a non-voting read-serving
    replica (README "Read plane"): it receives the replication
    stream, serves reads/watches/sessions and forwards writes like
    any follower, but never stands in an election, never grants a
    claim, and its replication acks never count toward the
    quorum-commit majority.  ``voters`` is the VOTING membership size
    (observer peers excluded); default = every peer plus self, the
    observer-free legacy shape."""
    from .persist import (
        WriteAheadLog,
        attach_wal,
        entry_zxid,
        reap_orphan_ephemerals,
        recover_state,
        reset_dir,
        restore_sequential_counters,
        restore_sessions,
    )
    from .replication import (
        RemoteLeader,
        RemoteReplicaStore,
        ReplicationService,
    )
    from .server import ZKServer
    from .store import ZKDatabase

    os.makedirs(wal_dir, exist_ok=True)
    rec = recover_state(wal_dir)
    # live-state handles the peer's vote replies read through
    state = {
        'epoch': rec.epoch,
        'zxid_fn': (lambda: rec.zxid),
    }
    voting_total = voters if voters is not None else len(peers) + 1
    if rec.config is not None and rec.config.get('voters'):
        # a reconfig record on disk supersedes the spawn-time shape:
        # this member votes (and counts quorums) at the membership it
        # last durably learned
        voting_total = len(rec.config['voters'])
    peer = ElectionPeer(member_id, peers, total=voting_total,
                        port=election_port, seed=member_id,
                        promise_dir=wal_dir, observer=observer)
    peer.epoch_fn = lambda: state['epoch']
    peer.zxid_fn = lambda: state['zxid_fn']()
    await peer.start()

    server: ZKServer | None = None
    wal: WriteAheadLog | None = None
    store = None                      # RemoteReplicaStore while following
    remote = None
    led_db = None                     # ZKDatabase of a deposed ex-leader
    loop = asyncio.get_running_loop()
    redial = PEER_POLICY.backoff(member_id)

    def announce(srv: ZKServer) -> None:
        nonlocal server
        first = server is None
        server = srv
        if first:
            if ready_cb is not None:
                ready_cb(srv)
            else:
                print('READY %d %d' % (srv.port, peer.port),
                      flush=True)

    while True:
        decision = await peer.resolve()
        if decision[0] == 'lead':
            target_epoch = decision[1]
            if store is not None:
                # live promotion: the mirror this follower served
                # reads from becomes the leader database — catch up
                # first, keep the (already-open) mirror WAL as the
                # leader's log so the on-disk history continues.
                # The store's OWN leader handle, not the `remote`
                # var: a failed re-dial may have nulled the latter
                # while the store still mirrors the previous leader.
                src = store.leader
                store.catch_up()
                db = ZKDatabase()
                db.nodes = store.nodes
                db.zxid = store.zxid
                db.epoch = src.epoch
                db.log_start_zxid = db.zxid
                src.close()
                attach_wal(db, wal)
                # durable sessions survive the failover: the mirror's
                # replicated session table seats into the new leader
                # database (fresh expiry clocks; a client that
                # resumes inside the timeout keeps its ephemerals)
                restore_sessions(db, src.session_snapshot())
                # so does the membership config the mirror replicated
                # (including an in-progress joint window)
                if src.config is not None:
                    db.install_config(src.config)
            elif led_db is not None:
                # a deposed ex-leader re-winning (the successor era
                # ended before this member ever re-followed): its own
                # database stands, WAL still attached
                db = led_db
            else:
                # cold promotion: the whole ensemble died; this
                # member's WAL seeds the new quorum (the acceptance
                # path — any member's disk can)
                from .persist import open_wal_database
                db = open_wal_database(wal_dir, sync=sync)
                wal = db.wal
            restore_sequential_counters(db)
            new_epoch = max(target_epoch, db.epoch + 1)
            db.bump_epoch(new_epoch)
            reap_orphan_ephemerals(db)
            if db.voter_ids is None and voter_ids is not None:
                # never-reconfigured ensemble: install the spawn
                # shape as config version 0 so the rcfg admin
                # channel (server/server.py) has a base to change
                db.install_config({
                    'version': 0, 'phase': 'final',
                    'voters': tuple(voter_ids), 'old_voters': None,
                    'observers': tuple(observer_ids or ())})
            if db.old_voter_ids is not None:
                # an in-progress reconfig survived (recovered from
                # WAL control records, or inherited from the mirror):
                # the new leader finishes it — the final record
                # commits under the fresh epoch, closing the joint
                # window instead of wedging quorum math on a fleet
                # that may never reassemble C_old
                db.commit_reconfig()
                log.info('member %d completed recovered reconfig '
                         '(config version %d)', member_id,
                         db.config_version)
            if db.voter_ids is not None:
                voting_total = len(db.voter_ids)
                peer.total = voting_total
            # quorum-commit: the VOTING membership is the voter set
            # (observer mirrors ack for the truncation floor but
            # never toward the majority), so a write acked through
            # THIS leader is majority-held before the ack leaves
            svc = await ReplicationService(
                db, total=voting_total).start()
            state['epoch'] = new_epoch
            state['zxid_fn'] = lambda db=db: db.zxid
            store = None
            remote = None
            led_db = None
            peer.note_leading(svc.port)
            if server is None:
                srv = ZKServer(db, port=client_port,
                               member='m%d' % (member_id,),
                               blackbox_dir=wal_dir)
                srv.quorum = svc.quorum
                announce(await srv.start())
            else:
                server.quorum = svc.quorum
                server.repoint(db, role='leader')
            svc.quorum.trace = getattr(db, 'trace', None)
            # OS-tier fencing of DIRECT client writes: once this
            # service learns it is deposed, every write through this
            # member bounces with EPOCH_FENCED (same check the
            # forwarded path applies)
            server.fence = (lambda s=svc: s.deposed)

            def _member_reconfig(phase, entry, q=svc.quorum,
                                 p=peer) -> None:
                # a reconfig committed while leading repoints the
                # quorum denominator and this peer's election total.
                # The OS tier's gate is count-based (follower tokens
                # are anonymous uuids): during a joint window it
                # holds the STRICTER of the two configs' majorities
                # by count; the in-process tier carries the full
                # named-set joint rule (server/replication.py).
                if db.voter_ids is None:
                    return
                n = len(db.voter_ids)
                if phase == 'joint' and db.old_voter_ids is not None:
                    n = max(n, len(db.old_voter_ids))
                q.total = n
                p.total = len(db.voter_ids)
            db.on_config_change = _member_reconfig
            server.elections += 1
            log.info('member %d leading at epoch %d (zxid %d)',
                     member_id, new_epoch, db.zxid)
            # lead until killed — or until the supersession watch
            # sees a standing leader at a higher epoch (this member
            # was partitioned away and deposed): fence, step down,
            # rejoin.  The poll period bounds how long a deposed
            # leader can keep acking direct writes.
            while True:
                await asyncio.sleep(LEAD_WATCH_S)
                sup = [r for r in await peer._poll()
                       if r[2] == 'leading' and r[3] > new_epoch]
                if sup:
                    svc.depose(max(r[3] for r in sup))
                    break
            await svc.stop()
            led_db = db
            peer.note_looking()
            await asyncio.sleep(redial.next_delay() / 1000.0)
            continue
        else:
            _lid, host, repl_port, lepoch = decision[1]
            if store is not None:
                have_zxid = store.zxid
                recovered = {'zxid': store.zxid, 'nodes': store.nodes}
                cur_epoch = remote.epoch if remote is not None \
                    else state['epoch']
                prev_sessions = store.leader.session_snapshot()
            elif led_db is not None:
                # a deposed ex-leader rejoining the current era: its
                # led state is the catch-up base (the successor holds
                # at least as much acked history — the vote rule —
                # and anything extra here was never acked under the
                # new epoch, so a snapshot bootstrap may discard it:
                # ZAB truncation semantics)
                have_zxid = led_db.zxid
                recovered = {'zxid': led_db.zxid,
                             'nodes': led_db.nodes}
                cur_epoch = led_db.epoch
                prev_sessions = led_db.session_snapshot()
            else:
                have_zxid = rec.zxid if (
                    rec.last_index or rec.snapshot_index >= 0) else None
                recovered = ({'zxid': rec.zxid, 'nodes': rec.nodes}
                             if have_zxid is not None else None)
                cur_epoch = rec.epoch
                prev_sessions = rec.sessions
            if remote is not None:
                remote.close()
            remote = RemoteLeader(host, repl_port,
                                  have_zxid=have_zxid,
                                  epoch=cur_epoch,
                                  observer=observer)
            # the durable session table this member already holds (a
            # mirror it served, a led era, or its recovered WAL)
            # seeds the new mirror handle — resync ships only the
            # tail, and a later promotion must keep these sessions
            remote.seed_sessions(prev_sessions)
            # the leader-lost latch is one-shot: arm it BEFORE the
            # connect so an EOF landing while the server below is
            # still starting cannot fire into a missing callback and
            # wedge this member 'following' a dead leader
            lost = asyncio.Event()
            remote.on_leader_lost = \
                lambda: loop.call_soon_threadsafe(lost.set)
            try:
                await remote.connect()
            except (OSError, ConnectionError, asyncio.TimeoutError,
                    TimeoutError):
                # the would-be leader died between poll and dial:
                # back off and re-enter the election loop
                remote.close()
                remote = None
                await asyncio.sleep(redial.next_delay() / 1000.0)
                continue
            redial.reset()
            store = RemoteReplicaStore(remote, lag=0.0,
                                       recovered=recovered)
            # a reconfig record arriving over replication repoints
            # this follower's election total live (count-based at
            # this tier; a joint window holds the stricter of the
            # two configs' majorities by count)
            store.on_config_applied = (
                lambda cfg, p=peer: setattr(
                    p, 'total',
                    max(len(cfg['voters']),
                        len(cfg.get('old_voters') or ()))))
            if not remote.resynced:
                # snapshot bootstrap: the on-disk history is stale
                # relative to the installed image — reset and
                # re-anchor (same dance as the static follower worker)
                if wal is not None:
                    wal.close()
                    wal = None
                reset_dir(wal_dir)
            if wal is None:
                wal = WriteAheadLog(wal_dir, sync=sync)
            wal.bind(store)
            wal.snapshot_gate = (
                lambda s=store, r=remote: s.applied == r.log_end())
            with remote._mirror_lock:
                for e in remote.log:
                    if entry_zxid(e) > wal.last_zxid:
                        wal.append(e)
                remote.wal = wal
                if remote.epoch > cur_epoch:
                    wal.append(('epoch', remote.epoch, wal.last_zxid))
                    wal.sync_for_flush()   # the fence must be durable
            if not remote.resynced:
                wal.snapshot_now()
            state['epoch'] = remote.epoch or lepoch
            state['zxid_fn'] = lambda s=store: s.zxid
            led_db = None                 # rejoined the current era
            peer.note_following()
            member_role = 'observer' if observer else 'follower'
            if server is None:
                srv = await ZKServer(
                    remote, store=store, port=client_port,
                    member='m%d' % (member_id,),
                    blackbox_dir=wal_dir).start()
                srv.role = member_role
                announce(srv)
            else:
                # a follower's acks gate on its mirror WAL alone: the
                # quorum half belongs to the leader's RPC response
                server.quorum = None
                server.repoint(remote, store=store, role=member_role)
            # a follower at the current epoch is not fenced: stale-
            # epoch protection for its forwarded writes lives in the
            # RPC stamp (the service bounces them)
            server.fence = None
            server.elections += 1
            log.info('member %d following %s:%d at epoch %d',
                     member_id, host, repl_port, remote.epoch)
            await lost.wait()
            # push-channel EOF: jittered backoff, then re-elect —
            # every surviving follower does the same, decorrelated
            await asyncio.sleep(redial.next_delay() / 1000.0)

# ---------------------------------------------------------------------
# Process-tier campaign driver: the seeded OS-process election
# schedule.  Shared by ``zkstream_tpu chaos --tier process`` and
# tests/test_process_ensemble.py so the checks cannot drift.
# ---------------------------------------------------------------------

MEMBER_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             'member_worker.py')

#: bounded waits for the process tier (spawn + recovery + election)
PROC_READY_S = 45.0
PROC_LEADER_S = 45.0


class ProcMember:
    """One spawned member process and its fixed ports.
    ``observer=True`` spawns a non-voting read-serving member
    (``member_worker.py --observer``)."""

    def __init__(self, member_id: int, wal_dir: str,
                 client_port: int, election_port: int,
                 observer: bool = False):
        self.member_id = member_id
        self.wal_dir = wal_dir
        self.client_port = client_port
        self.election_port = election_port
        self.observer = observer
        self.proc = None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def spawn(self, peers) -> 'ProcMember':
        import subprocess
        import sys
        args = [sys.executable, MEMBER_WORKER, str(self.member_id),
                self.wal_dir, str(self.client_port),
                str(self.election_port)]
        if self.observer:
            args.append('--observer')
        args += ['%d:127.0.0.1:%d%s'
                 % (m.member_id, m.election_port,
                    ':observer' if m.observer else '')
                 for m in peers if m.member_id != self.member_id]
        self.proc = subprocess.Popen(
            args, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        return self

    async def wait_ready(self, timeout: float = PROC_READY_S) -> None:
        loop = asyncio.get_running_loop()
        line = await asyncio.wait_for(
            loop.run_in_executor(None, self.proc.stdout.readline),
            timeout)
        assert line.startswith('READY '), (self.member_id, line)

    def kill(self) -> None:
        """SIGKILL: the OS severs every socket, RAM is gone."""
        import signal
        if self.alive():
            os.kill(self.proc.pid, signal.SIGKILL)
        if self.proc is not None:
            self.proc.wait()
            self.proc.stdout.close()
            self.proc = None


async def _scrape_mntr(port: int, timeout: float = 2.0) -> dict:
    """Raw-TCP mntr scrape of one member -> {key: value}."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection('127.0.0.1', port), timeout)
    try:
        writer.write(b'mntr')
        await writer.drain()
        data = await asyncio.wait_for(reader.read(), timeout)
    finally:
        writer.close()
    out = {}
    for line in data.decode('utf-8', 'replace').splitlines():
        if '\t' in line:
            k, v = line.split('\t', 1)
            out[k] = v
    return out


async def _rcfg(port: int, line: str, timeout: float = 8.0) -> str:
    """One raw-TCP ``rcfg`` admin line against one member -> reply."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection('127.0.0.1', port), timeout)
    try:
        writer.write(('rcfg %s\n' % (line,)).encode())
        await writer.drain()
        data = await asyncio.wait_for(reader.read(), timeout)
    finally:
        writer.close()
    return data.decode('utf-8', 'replace')


async def find_leader(members, min_epoch: int = 0,
                      timeout: float = PROC_LEADER_S):
    """Poll the live members' mntr rows until one reports
    ``zk_member_role == 'leader'`` at ``zk_epoch >= min_epoch``.
    Returns ``(member_id, epoch)``; raises TimeoutError when no such
    leader stands inside the window."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for m in members:
            if not m.alive():
                continue
            try:
                rows = await _scrape_mntr(m.client_port)
            except (OSError, asyncio.TimeoutError, TimeoutError):
                continue
            if rows.get('zk_member_role') == 'leader':
                epoch = int(rows.get('zk_epoch', 0))
                if epoch >= min_epoch:
                    return m.member_id, epoch
        await asyncio.sleep(0.15)
    raise TimeoutError('no leader at epoch >= %d within %.0fs'
                       % (min_epoch, timeout))


async def run_process_schedule(seed: int, ops: int = 6,
                               members: int = 3, elections: int = 2,
                               generations: int = 2,
                               workdir: str | None = None,
                               clients: int | None = None,
                               observers: int = 0,
                               reconfig: bool = False,
                               cached: bool = False):
    """One seeded OS-process election schedule: spawn ``members``
    symmetric peer processes over per-member WAL dirs, drive a seeded
    workload THROUGH THE LEADER (quorum-commit makes its ack
    survivable), SIGKILL the elected leader ``elections`` times —
    each kill immediately after a freshly acked marker write, which
    must read back from the successor — (each survivor set must elect
    a successor at a strictly higher epoch, operator-free), then
    SIGKILL the WHOLE ensemble ``generations`` times — each
    generation must elect from recovered WALs alone and still hold
    every acked write.  Invariant
    7 (at-most-one-leader-per-epoch, epoch monotonicity) is checked
    over the recorded history; violations carry the seed, rerunnable
    via ``zkstream_tpu chaos --tier process --seed N``.

    ``clients`` > 1 runs every workload phase as N CONCURRENT
    clients contending on a small shared key set, each op recorded
    as a two-sided interval (``History.invoke``/``settle``), and the
    schedule ends with the per-key WGL linearizability pass
    (analysis/linearize.py, invariant 9) pinned to the final key
    states read back through the elected leader — the OS-process
    half of the concurrent tier (``chaos --tier process --clients
    N``)."""
    import random
    import tempfile

    from ..analysis.linearize import check_linearizable
    from ..client import Client
    from ..io.faults import ScheduleResult, record_settle_error
    from ..io.invariants import (AMBIGUOUS_CODES, History,
                                 check_election, check_reconfig)
    from ..protocol.errors import ZKError, ZKProtocolError

    rng = random.Random('proc/%d' % (seed,))
    #: observer churn draws come from their OWN stream: attaching
    #: observers must not perturb the schedule existing seeds pin
    orng = random.Random('proc-obs/%d' % (seed,))
    #: reconfig victim draws likewise (``--reconfig`` joins the rerun
    #: key; existing pinned seeds see zero draws from this stream)
    prng = random.Random('proc-reconfig/%d' % (seed,))
    if reconfig and observers == 0:
        # the replace-voter swap needs a non-voting member to promote:
        # --reconfig implies at least one observer (part of the flag's
        # rerun-key semantics, like --observers itself)
        observers = 1
    res = ScheduleResult(seed=seed, tier='process',
                         clients=clients if clients else 1)
    h = History()
    root = workdir or tempfile.mkdtemp(prefix='zkproc-elect-')
    own_root = workdir is None
    total = members + observers
    ports = allocate_ports(2 * total)
    fleet = [ProcMember(i, os.path.join(root, 'm%d' % i),
                        ports[2 * i], ports[2 * i + 1],
                        observer=i >= members)
             for i in range(total)]
    expected: dict[str, bytes] = {}
    deleted: set[str] = set()

    def record_election(member_id: int, epoch: int) -> None:
        h.election(member_id, epoch)
        res.elections += 1

    async def fresh_client(leader_id: int) -> Client:
        """A client preferring the LEADER member: quorum-commit makes
        the leader's own ack survivable — it leaves only once a
        majority of mirrors has ingested the txn — so the schedule
        writes through the leader and asserts exactly that (the
        follower-routing workaround this schedule used to need is
        gone).  With observers attached the client runs with the
        read plane on (the ensemble tier's rule: `--observers` puts
        the distributed, zxid-gated read path under test here too)."""
        backends = [('127.0.0.1', m.client_port) for m in fleet
                    if m.alive() and m.member_id == leader_id]
        backends += [('127.0.0.1', m.client_port) for m in fleet
                     if m.alive() and m.member_id != leader_id]
        c = Client(servers=backends, shuffle_backends=False,
                   session_timeout=12000, op_timeout=3000,
                   seed=seed, read_distribution=observers > 0,
                   # --cached: the watch-backed cache plane rides
                   # the OS-process tier too (cache=False pins the
                   # knob off regardless of ZKSTREAM_CACHE)
                   cache='/' if cached else False,
                   connect_policy=BackoffPolicy(timeout=2000,
                                                retries=4, delay=100,
                                                cap=1000))
        c.start()
        await c.wait_connected(timeout=20)
        return c

    async def retrying(coro_fn, attempts=30, delay=0.25):
        last = None
        for _ in range(attempts):
            try:
                return await coro_fn()
            except ZKError as e:
                # a definite server verdict (NODE_EXISTS, NO_NODE,
                # BAD_VERSION, EPOCH_FENCED...) will not change on
                # retry — only the outcome-unknown family is worth
                # waiting out (io/invariants.py AMBIGUOUS_CODES)
                if e.code not in AMBIGUOUS_CODES:
                    raise
                last = e
                await asyncio.sleep(delay)
            except (ZKProtocolError, OSError) as e:
                last = e               # connection churn: retryable
                await asyncio.sleep(delay)
        raise last

    async def workload(phase: int, leader_id: int) -> None:
        c = await fresh_client(leader_id)
        try:
            for i in range(ops):
                res.ops += 1
                kind = rng.choice(('create', 'create', 'set', 'get'))
                path = '/p%d-%d' % (phase, i)
                try:
                    if kind == 'create':
                        data = b'd%d-%d' % (phase, i)
                        await retrying(
                            lambda p=path, d=data: c.create(p, d))
                        expected[path] = data
                        h.acked_create(path, data, 0)
                        res.acked += 1
                    elif kind == 'set' and expected:
                        p = rng.choice(sorted(expected))
                        data = b'v%d-%d' % (phase, i)
                        await retrying(
                            lambda p=p, d=data: c.set(p, d,
                                                      version=-1))
                        expected[p] = data
                        res.acked += 1
                    else:
                        if expected:
                            p = rng.choice(sorted(expected))
                            await retrying(lambda p=p: c.get(p))
                except (ZKError, ZKProtocolError) as e:
                    res.typed_errors += 1
                    log.info('workload op failed (typed): %s', e)
        finally:
            await c.close()

    #: the concurrent phases' shared, contended key set
    lin_keys = ('/lk0', '/lk1', '/lk2')

    async def concurrent_workload(phase: int, leader_id: int) -> None:
        """The ``clients`` > 1 workload phase: N concurrent clients
        over :data:`lin_keys`, every op an interval record.  No
        retry loop — a churn-felled attempt settles as its own
        outcome-unknown interval, exactly what the checker models."""

        async def one(ci: int) -> None:
            c = await fresh_client(leader_id)
            crng = random.Random('proc-client/%d/%d/%d'
                                 % (seed, phase, ci))
            spans = [None]
            c.on_op = lambda span: spans.__setitem__(0, span)
            # each phase's client is a FRESH session: the history's
            # client id is phase-qualified so the session-monotone
            # read check (check_session_reads) floors each session
            # separately instead of chaining floors across sessions
            # that share no lastZxidSeen carry
            hci = phase * clients + ci
            try:
                for i in range(ops):
                    res.ops += 1
                    kind = crng.choice(('create', 'set', 'set',
                                        'get', 'get'))
                    key = crng.choice(lin_keys)
                    tag = b'p%d-c%d-%d' % (phase, ci, i)
                    call = h.invoke(kind, key, client=hci,
                                    data=tag if kind != 'get'
                                    else None)
                    try:
                        if kind == 'create':
                            await asyncio.wait_for(
                                c.create(key, tag), 8)
                            span = spans[0]
                            h.settle(call, 'ok',
                                     zxid=span.zxid
                                     if span is not None else None)
                            res.acked += 1
                        elif kind == 'set':
                            stat = await asyncio.wait_for(
                                c.set(key, tag, version=-1), 8)
                            h.settle(call, 'ok', zxid=stat.mzxid,
                                     version=stat.version)
                            res.acked += 1
                        else:
                            got, stat = await asyncio.wait_for(
                                c.get(key), 8)
                            h.settle(call, 'ok', zxid=stat.mzxid,
                                     data=bytes(got),
                                     version=stat.version)
                    except (ZKError, ZKProtocolError) as e:
                        record_settle_error(res, h, call, e)
                    except (asyncio.TimeoutError, TimeoutError):
                        h.settle(call, 'unknown',
                                 error='HARD_BOUND')
            finally:
                await c.close()

        await asyncio.gather(*(one(ci) for ci in range(clients)))

    work = concurrent_workload if clients and clients > 1 \
        else workload

    async def verify(leader_id: int, context: str) -> None:
        c = await fresh_client(leader_id)
        try:
            await retrying(lambda: c.sync('/'))
            for path, data in sorted(expected.items()):
                if path in deleted:
                    continue
                try:
                    got, _stat = await retrying(
                        lambda p=path: c.get(p))
                except (ZKError, ZKProtocolError) as e:
                    res.violations.append(
                        '%s: acked create %s lost (%s)'
                        % (context, path, e))
                    continue
                if bytes(got) != data:
                    res.violations.append(
                        '%s: acked write %s holds %r, expected %r'
                        % (context, path, bytes(got), data))
        finally:
            await c.close()

    #: the schedule's view of the LOGICAL membership (member ids):
    #: starts at the spawn shape, moves with every applied reconfig.
    #: Spawn roles stay fixed — this tier is count-based (see
    #: run_member) — but quorum denominators and election totals
    #: follow these sets through the replicated CONTROL records.
    cfg_voters = sorted(range(members))
    cfg_observers = sorted(range(members, total))

    def _pick_swap(leader_id: int):
        """One replace-voter shape: a non-leader voter demotes to
        observer, an observer promotes into the voter set (sizes
        preserved, so every later quorum stays satisfiable)."""
        cands = [v for v in cfg_voters if v != leader_id]
        v = cands[prng.randrange(len(cands))]
        o = cfg_observers[prng.randrange(len(cfg_observers))]
        new_voters = sorted([x for x in cfg_voters if x != v] + [o])
        new_obs = sorted([x for x in cfg_observers if x != o] + [v])
        return v, o, new_voters, new_obs

    async def reconfig_round(leader_id: int, epoch: int) -> None:
        """One fenced replace-voter reconfiguration through the rcfg
        admin channel: ``apply`` lands the joint record, awaits its
        quorum, commits, awaits the final record — the process tier's
        analogue of the ensemble tier's forced reconfig step."""
        nonlocal cfg_voters, cfg_observers
        v, o, new_voters, new_obs = _pick_swap(leader_id)
        line = 'apply %s %s' % (','.join(map(str, new_voters)),
                                ','.join(map(str, new_obs)) or '-')
        try:
            reply = await asyncio.wait_for(
                _rcfg(fleet[leader_id].client_port, line), 20)
        except (OSError, asyncio.TimeoutError, TimeoutError) as e:
            res.violations.append(
                'rcfg apply (replace %d->%d) did not complete: %s'
                % (v, o, e))
            return
        if reply.startswith('applied'):
            version = int(reply.split('version=')[1].split()[0])
            cfg_voters, cfg_observers = new_voters, new_obs
            h.reconfig(version, 'final', epoch, voters=new_voters,
                       observers=new_obs)
            h.member_event('reconfig-replace-voter(%d->%d)'
                           % (v, o), o)
        elif reply.startswith('error'):
            # a legal fence refusal (one voter change per epoch) is
            # a recorded non-event, not a violation
            h.member_event('reconfig-refused(%s)'
                           % (reply.strip(),), v)
        else:
            res.violations.append(
                'rcfg apply (replace %d->%d) unexpected reply %r'
                % (v, o, reply))

    try:
        for m in fleet:
            m.spawn(fleet)
        for m in fleet:
            await m.wait_ready()
        leader_id, epoch = await find_leader(fleet, min_epoch=1)
        record_election(leader_id, epoch)

        # -- elected-leader kill loop: >= `elections` forced ---------
        for round_no in range(elections):
            await work(round_no, leader_id)
            if observers and orng.random() < 0.5:
                # observer churn (own RNG stream): SIGKILL one and
                # respawn it — it must recover its mirror WAL and
                # re-follow without ever standing in the election
                ob = fleet[members + orng.randrange(observers)]
                if ob.alive():
                    h.member_event('kill-observer', ob.member_id)
                    ob.kill()
                    ob.spawn(fleet)
                    await ob.wait_ready()
                    h.member_event('restart', ob.member_id)
            victim = next(m for m in fleet
                          if m.member_id == leader_id)
            # leader-killed-after-ack: one marker write THROUGH THE
            # LEADER, then SIGKILL it the instant the ack returns —
            # quorum-commit means the ack implies a majority of
            # mirrors holds the txn, so it must survive the election
            # and read back from the successor (verify below)
            c = await fresh_client(leader_id)
            try:
                path = '/killmark%d' % (round_no,)
                data = b'k%d' % (round_no,)
                await retrying(lambda: c.create(path, data))
                expected[path] = data
                h.acked_create(path, data, 0)
                res.acked += 1
            finally:
                await c.close()
            h.member_event('kill-leader-after-ack', leader_id)
            victim.kill()
            # the survivors elect with no operator; the dead member
            # respawns over its own WAL and must rejoin as follower
            leader_id, epoch = await find_leader(
                fleet, min_epoch=epoch + 1)
            record_election(leader_id, epoch)
            victim.spawn(fleet)
            await victim.wait_ready()
            h.member_event('restart', victim.member_id)
            await verify(leader_id, 'after election %d' % (round_no,))
            if reconfig and (round_no < elections - 1
                             or not generations):
                # one voter replace per freshly elected era (the
                # at-most-one-voter-change-per-epoch fence clears on
                # every leader kill above).  The LAST era's voter-
                # change budget is reserved for the mid-joint
                # SIGKILL below — same epoch, same fence.
                await reconfig_round(leader_id, epoch)
        await work(elections, leader_id)

        # -- full-ensemble SIGKILL -> election from recovered WALs --
        for gen in range(generations):
            pending = None
            if reconfig and gen == 0:
                # land the JOINT record only, then SIGKILL the whole
                # ensemble mid-window: recovery must finish the
                # reconfig from WAL CONTROL records alone (the new
                # leader's commit_reconfig on promotion) — or, if the
                # record never reached a durable majority, roll back
                # to the pre-propose config.  Either way the joint
                # window must not survive recovery.
                v, o, nv, no = _pick_swap(leader_id)
                line = 'propose %s %s' % (','.join(map(str, nv)),
                                          ','.join(map(str, no))
                                          or '-')
                try:
                    reply = await asyncio.wait_for(
                        _rcfg(fleet[leader_id].client_port, line), 20)
                except (OSError, asyncio.TimeoutError,
                        TimeoutError) as e:
                    res.violations.append(
                        'rcfg propose mid-joint failed: %s' % (e,))
                    reply = ''
                if reply.startswith('proposed'):
                    h.member_event('sigkill-mid-joint(%d->%d)'
                                   % (v, o), 'ensemble')
                    pending = (nv, no)
                elif reply.startswith('error'):
                    h.member_event('reconfig-refused(%s)'
                                   % (reply.strip(),), v)
            h.member_event('sigkill-all(gen %d)' % (gen,), 'ensemble')
            for m in fleet:
                m.kill()
            for m in fleet:
                m.spawn(fleet)
            for m in fleet:
                await m.wait_ready()
            prev = epoch
            leader_id, epoch = await find_leader(
                fleet, min_epoch=prev + 1)
            if epoch <= prev:
                res.violations.append(
                    'generation %d: epoch did not increase across '
                    'full-ensemble recovery (%d -> %d)'
                    % (gen, prev, epoch))
            record_election(leader_id, epoch)
            if reconfig:
                # the joint window must be resolved (gen 0), and the
                # resolved config must keep surviving every further
                # generation of full-ensemble SIGKILL
                try:
                    status = await asyncio.wait_for(
                        _rcfg(fleet[leader_id].client_port,
                              'status'), 20)
                except (OSError, asyncio.TimeoutError,
                        TimeoutError) as e:
                    status = ''
                    res.violations.append(
                        'generation %d: rcfg status unreadable '
                        'after recovery: %s' % (gen, e))
                if status and 'phase=final' not in status:
                    res.violations.append(
                        'generation %d: joint config survived '
                        'full-ensemble recovery (%r)'
                        % (gen, status.strip()))
                elif status and pending is not None:
                    version = int(
                        status.split('version=')[1].split()[0])
                    voters_csv = status.split('voters=')[1].split()[0]
                    got = sorted(int(x) for x in voters_csv.split(',')
                                 if x and x != '-')
                    if got == pending[0]:
                        cfg_voters, cfg_observers = pending
                        h.reconfig(version, 'final', epoch,
                                   voters=cfg_voters,
                                   observers=cfg_observers)
                        h.member_event(
                            'reconfig-recovered(v%d)' % (version,),
                            'ensemble')
                    elif got == cfg_voters:
                        h.member_event('reconfig-rolled-back',
                                       'ensemble')
                    else:
                        res.violations.append(
                            'generation %d: recovered voter set %s '
                            'matches neither the proposed %s nor '
                            'the prior %s config'
                            % (gen, got, pending[0], cfg_voters))
            await verify(leader_id,
                         'generation %d (recovered WALs)' % (gen,))
            # one more acked write per generation: the recovered
            # quorum must be writable, and the next generation must
            # carry this write too
            c = await fresh_client(leader_id)
            try:
                path, data = '/gen%d' % (gen,), b'g%d' % (gen,)
                await retrying(lambda: c.create(path, data))
                expected[path] = data
                h.acked_create(path, data, 0)
                res.acked += 1
            finally:
                await c.close()

        if clients and clients > 1:
            # invariant 9 over the concurrent phases: every shared
            # key's interval history must linearize, pinned to the
            # final state read back through the elected leader (the
            # writes survived generations of SIGKILL by now).  Only
            # a definite verdict pins a key: NO_NODE = absent, data
            # = present; a key whose read-back exhausted its retries
            # (connection churn) is left OUT of the mapping, which
            # check_linearizable treats as unconstrained — never as
            # absent, which would fabricate a lost-update finding.
            c = await fresh_client(leader_id)
            finals: dict = {}
            try:
                try:
                    await retrying(lambda: c.sync('/'))
                except (ZKError, ZKProtocolError, OSError):
                    pass               # a barrier, not an op
                for key in lin_keys:
                    try:
                        got, _stat = await retrying(
                            lambda k=key: c.get(k))
                        finals[key] = bytes(got)
                    except ZKError as e:
                        if e.code == 'NO_NODE':
                            finals[key] = None
                    except (ZKProtocolError, OSError):
                        pass               # unpinned, not absent
            finally:
                await c.close()
            res.violations.extend(check_linearizable(h, finals))
            # the session-monotone read gate's acceptance on THIS
            # tier too (analysis/linearize.py): a session must never
            # observe state older than it has already seen
            from ..analysis.linearize import check_session_reads
            res.violations.extend(check_session_reads(h))
        res.violations.extend(check_election(h))
        res.violations.extend(check_reconfig(h))
        if reconfig and not h.of_kind('reconfig'):
            res.violations.append(
                'reconfig schedule completed no membership change '
                '(every rcfg apply refused or rolled back)')
        if observers:
            # observers must never have stood: every recorded
            # election winner is a voter, and every live observer
            # still reports the observer role
            for r in h.of_kind('election'):
                if isinstance(r['member'], int) \
                        and r['member'] >= members:
                    res.violations.append(
                        'observer %s won an election at epoch %d '
                        '(observers must never stand)'
                        % (r['member'], r['epoch']))
            for ob in fleet[members:]:
                if not ob.alive():
                    continue
                try:
                    rows = await _scrape_mntr(ob.client_port)
                except (OSError, asyncio.TimeoutError, TimeoutError):
                    continue
                if rows.get('zk_member_role') != 'observer':
                    res.violations.append(
                        'member %d spawned as observer reports role '
                        '%r' % (ob.member_id,
                                rows.get('zk_member_role')))
            # read scale-out correctness: the acked tree must read
            # back through an OBSERVER too (sync barrier first — the
            # forwarded RPC piggyback is the catch-up)
            await verify(fleet[members].member_id,
                         'read-back through observer %d'
                         % (fleet[members].member_id,))
        return res
    except (TimeoutError, asyncio.TimeoutError) as e:
        res.violations.append('process schedule stalled: %s' % (e,))
        return res
    finally:
        for m in fleet:
            try:
                m.kill()
            except Exception:
                pass
        res.history = list(h.records)
        res.member_events = h.member_timeline()
        # black-box harvest (utils/blackbox.py): every member of this
        # tier — the SIGKILL'd ones especially — left a flight-
        # recorder ring in its wal_dir; lift the dead fleet's last
        # spans into member_rings before the root is torn down, so
        # the OS-process tier's --trace-out timeline has member rings
        # at all (its servers live in child processes, so the
        # in-process ring dump path never sees them)
        from ..utils.blackbox import harvest_spans
        for m in fleet:
            try:
                for key, spans in harvest_spans(m.wal_dir).items():
                    res.member_rings.setdefault(key, spans)
            except Exception:
                pass                  # salvage is best-effort
        if own_root:
            import shutil
            shutil.rmtree(root, ignore_errors=True)


async def run_process_campaign(base_seed: int, schedules: int,
                               ops: int = 6, progress=None,
                               elections: int | None = None,
                               clients: int | None = None,
                               observers: int | None = None,
                               reconfig: bool = False,
                               cached: bool = False):
    """Consecutive seeded process-tier schedules from ``base_seed``.
    ``elections`` overrides the per-schedule forced leader-kill count,
    ``clients`` > 1 makes every workload phase concurrent with
    the linearizability pass at the end, ``observers`` attaches N
    non-voting read-serving members with their own churn stream, and
    ``reconfig`` drives a fenced voter replace through the rcfg admin
    channel per elected era plus one full-ensemble SIGKILL mid-joint
    (all part of the rerun key, like the ensemble tier's flags)."""
    out = []
    for i in range(schedules):
        r = await run_process_schedule(
            base_seed + i, ops=ops,
            elections=elections if elections is not None else 2,
            clients=clients,
            observers=observers if observers is not None else 0,
            reconfig=reconfig, cached=cached)
        out.append(r)
        if progress is not None:
            progress(r)
    return out
