"""Cross-process ensemble members: commit-log replication over TCP.

The in-process ``ZKEnsemble`` shares one ``ZKDatabase`` object between
its members, so killing a member is necessarily a cooperative close —
half-written frames, dead-socket detection and OS-level connection
resets are never exercised.  The reference's multi-node tier runs three
genuinely separate server processes and kills them with signals
(reference: test/multi-node.test.js:23-39,309-338; test/zkserver.js
hunts child PIDs for a clean kill).  This module gives the rebuild the
same tier: a **leader process** exporting its ``ZKDatabase`` over a
replication service, and **follower processes** running a full
``ZKServer`` whose leader-side operations forward over TCP while reads
and watches are served from a local :class:`~.store.ReplicaStore`
replaying the mirrored commit log — so ``SIGKILL`` on any follower
severs real client sockets at the OS level, and the session state the
clients depend on survives in the leader process, exactly the
single-leader replication model store.py already implements in-process.

Two channels per follower, paired by a token:

- ``control`` — a *blocking* socket the follower's request handlers
  call RPCs on (create/delete/set_data, session lifecycle, sync
  barrier).  Every response piggybacks the commit-log entries the
  follower has not mirrored yet, so a write-then-read through one
  member observes its own write without waiting on the async stream.
- ``events`` — an asyncio stream the leader pushes to: new commit-log
  entries as they land, and session-expiry broadcasts.

Wire format: 4-byte big-endian length + pickle.  Pickle is safe here
for the same reason the reference can shell out to a local JVM: both
ends are the same trusted test harness on one machine; this service
must never listen on a non-loopback interface.

Follower restart is supported the way real ZK does it: a follower
joining after history began (or rejoining after a SIGKILL) is
bootstrapped from a leader snapshot — the tree image plus its log
position — and replays only the tail from there.  Killing the leader
no longer kills the quorum: followers detect the push-channel EOF and
elect a replacement over their recovered (epoch, zxid) pairs
(server/election.py); every push and forwarded-write ack here is
stamped with the leadership epoch, stale-epoch pushes are rejected by
the mirror, and a deposed leader's forwarded writes bounce with a
typed EPOCH_FENCED error instead of being silently applied.
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import socket
import struct
import threading
import time

from ..protocol.consts import CreateFlag
from ..utils.events import EventEmitter
from .persist import entry_zxid
from .store import (
    ReplicaStore,
    ZKDatabase,
    ZKOpError,
    ZKServerSession,
    durable_sessions,
)

log = logging.getLogger('zkstream_tpu.server.replication')

_LEN = struct.Struct('>I')


class ZKLeaderLostError(ZKOpError):
    """The leader process died (or the control channel was severed)
    mid-RPC: the forwarded write's outcome is unknown.  Typed as
    ``CONNECTION_LOSS`` — the outcome-unknown code the client-side
    ambiguity accounting (io/invariants.py AMBIGUOUS_CODES) already
    classifies — so a follower's request handler converts it into an
    honest wire error instead of tearing the client connection down
    with a raw ``ConnectionError``."""

    def __init__(self, detail: str = ''):
        super().__init__('CONNECTION_LOSS')
        self.detail = detail


class ZKEpochFencedError(ZKOpError):
    """A write carried (or arrived at) a stale leadership epoch
    (server/election.py): definitively rejected, never applied."""

    def __init__(self):
        super().__init__('EPOCH_FENCED')


def _dump(msg) -> bytes:
    payload = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    return _LEN.pack(len(payload)) + payload


async def _read_msg(reader: asyncio.StreamReader):
    hdr = await reader.readexactly(4)
    (n,) = _LEN.unpack(hdr)
    return pickle.loads(await reader.readexactly(n))


def _recv_msg(sock: socket.socket):
    buf = b''
    while len(buf) < 4:
        chunk = sock.recv(4 - len(buf))
        if not chunk:
            raise ConnectionError('replication control channel closed')
        buf += chunk
    (n,) = _LEN.unpack(buf)
    out = b''
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError('replication control channel closed')
        out += chunk
    return pickle.loads(out)


# ---------------------------------------------------------------------
# Quorum-commit: the leader's ack means a majority holds the write.
# ---------------------------------------------------------------------

METRIC_QUORUM_ACK = 'zk_quorum_ack_ms'
QUORUM_ACK_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                      25.0, 50.0, 100.0, 250.0)

#: How long a gated flush waits for quorum before DEGRADING — the
#: release-on-attempt philosophy of the WAL's fsync gate: a quorum the
#: ensemble cannot currently assemble (partition, parked followers)
#: must delay acks, never wedge every reply forever.  Degraded
#: releases are counted (``degraded_releases``, mntr
#: ``zk_quorum_degraded``) and the quorum floor does NOT advance, so
#: the invariant engine's no-demotion rule stays honest.
DEFAULT_QUORUM_WAIT_MS = 250.0


def quorum_enabled() -> bool:
    """Global kill switch (mirrors ``ZKSTREAM_NO_WAL`` /
    ``ZKSTREAM_NO_ELECTION``): the fsync-only ack barrier stays
    available as the A/B validator arm (``bench.py --quorum``)."""
    return os.environ.get('ZKSTREAM_NO_QUORUM') != '1'


def quorum_wait_ms() -> float:
    try:
        v = float(os.environ.get('ZKSTREAM_QUORUM_WAIT_MS', ''))
    except ValueError:
        return DEFAULT_QUORUM_WAIT_MS
    return v if v > 0 else DEFAULT_QUORUM_WAIT_MS


def quorum_of(total: int) -> int:
    return total // 2 + 1


class QuorumGate:
    """The quorum half of the leader's ack barrier.

    Before this gate, a write acked THROUGH THE LEADER died with the
    leader: only the leader's tree (and WAL) held it, and
    ``run_process_schedule`` routed writes through followers purely to
    keep the no-acked-write-lost invariant honest.  The gate closes
    that gap: every follower ack piggybacks its mirror's newest
    ``applied_zxid`` (and accepted epoch) on the existing replication
    channels, and the leader's send plane holds a corked tick's acks —
    alongside the WAL's group fsync, one wait for both
    (:class:`CommitBarrier`) — until a majority of the ``total``
    membership (the leader's own vote included) holds every txn the
    tick acked.

    Fencing: an ack stamped with an epoch below the database's current
    one is a deposed era's — dropped and counted (``stale_acks``), so
    a partitioned ex-follower's late acks can never count toward a new
    epoch's quorum.

    Liveness: a quorum the ensemble cannot assemble degrades after
    ``wait_ms`` (:data:`DEFAULT_QUORUM_WAIT_MS`) — the corked acks
    leave quorum-unconfirmed, ``degraded_releases`` counts it, and the
    quorum floor stays put.  A single-member ensemble (``total < 2``)
    needs no gate at all: the leader IS the majority."""

    def __init__(self, db, total: int, *, enabled: bool | None = None,
                 collector=None, wait_ms: float | None = None):
        self.db = db
        self.total = total
        self.enabled = ((quorum_enabled() if enabled is None
                         else enabled) and total >= 2)
        self.wait_ms = wait_ms if wait_ms is not None \
            else quorum_wait_ms()
        #: voter key -> newest acked zxid (follower token / member id;
        #: the leader's own vote is ``db.zxid``, never stored here)
        self.acked: dict = {}
        #: Dynamic membership (server/store.py reconfig records).
        #: ``voters`` None = legacy count-based majority over
        #: ``total`` (bit-identical to pre-reconfig behavior).  When
        #: set, the majority is computed over the NAMED voter keys —
        #: and while ``old_voters`` stands (a joint window), over
        #: BOTH sets, taking the lower floor: no txn is quorum-held
        #: until a majority of C_old AND a majority of C_new hold it.
        #: ``leader_key`` names the member whose vote is ``db.zxid``.
        self.voters: set | None = None
        self.old_voters: set | None = None
        self.leader_key = None
        self.stale_acks = 0
        self.degraded_releases = 0
        #: newest zxid a majority is known to hold (cached; advanced
        #: by :meth:`note_ack`)
        self.quorum_zxid_floor = 0
        #: newest zxid already RELEASED unconfirmed by a degrade: the
        #: gate must not re-block later (read-only) ticks on a write
        #: that already left — each NEW write gets its own bounded
        #: wait, never a standing stall
        self.degraded_zxid = 0
        #: Optional utils/trace.TraceRing: the floor advancing leaves
        #: a ``QUORUM_ACK`` span between WAL_APPEND and the client ack
        #: in the zxid-keyed chain.
        self.trace = None
        self._waiters: list = []      # send-plane releases
        self._futs: list = []         # (target_zxid, Future) rpc waits
        self._timer = None
        self._commit_t: dict[int, float] = {}
        self._hist = None
        if collector is not None:
            self.bind_metrics(collector)

    def bind_metrics(self, collector) -> None:
        self._hist = collector.histogram(
            METRIC_QUORUM_ACK,
            'Commit to majority-ack latency, ms',
            buckets=QUORUM_ACK_BUCKETS)

    # -- feed --

    def note_pushed(self, zxid: int) -> None:
        """Stamp a commit's push time (latency measurement base for
        the zk_quorum_ack_ms histogram; bounded)."""
        if self.enabled and zxid not in self._commit_t \
                and len(self._commit_t) < 4096:
            self._commit_t[zxid] = time.monotonic()

    def note_ack(self, voter, zxid: int,
                 epoch: int | None = None) -> None:
        """One follower's piggybacked applied-zxid ack.  Epoch-fenced:
        a stale era's ack never counts toward the current quorum.
        Config-fenced: once a named voter set stands, an ack from a
        member outside it (a removed voter — the reconfig fence) is
        dropped and counted exactly like a stale epoch's."""
        if not self.enabled:
            return
        if epoch is not None and epoch < getattr(self.db, 'epoch', 0):
            self.stale_acks += 1
            return
        if self.voters is not None and voter != self.leader_key \
                and voter not in self.voters \
                and (self.old_voters is None
                     or voter not in self.old_voters):
            self.stale_acks += 1
            return
        if zxid <= self.acked.get(voter, 0):
            return
        self.acked[voter] = zxid
        self._advance()

    def forget(self, voter) -> None:
        """A follower detached: its standing vote leaves the pool
        (it can rejoin by acking again)."""
        self.acked.pop(voter, None)

    def set_config(self, voters, old_voters=None,
                   leader_key=None) -> None:
        """Install the named voter set(s) from a reconfig record
        (server/store.py): ``voters`` is C_new's ack keys,
        ``old_voters`` C_old's while a joint window stands.  A removed
        member's standing vote is forgotten immediately — it can
        neither hold up nor satisfy the new majority — and its later
        acks are fenced (``note_ack``)."""
        self.voters = set(voters) if voters is not None else None
        self.old_voters = (set(old_voters)
                           if old_voters is not None else None)
        if leader_key is not None:
            self.leader_key = leader_key
        if self.voters is not None:
            live = self.voters | (self.old_voters or set())
            for v in [v for v in self.acked if v not in live]:
                del self.acked[v]
        self._advance()

    def _majority_floor(self, keys, extra=None) -> int:
        """Majority floor over ONE named voter set: each member votes
        its acked zxid (0 when it never acked), the leader its own
        ``db.zxid``; ``extra = (key, zxid)`` counts one member's vote
        virtually (the forwarded-write grant)."""
        vals = []
        for k in keys:
            if k == self.leader_key:
                vals.append(self.db.zxid)
            elif extra is not None and k == extra[0]:
                vals.append(max(extra[1], self.acked.get(k, 0)))
            else:
                vals.append(self.acked.get(k, 0))
        if not vals:
            return 0
        vals.sort(reverse=True)
        return vals[quorum_of(len(vals)) - 1]

    def quorum_zxid(self) -> int:
        """The newest zxid a majority of the membership holds (the
        leader's own ``db.zxid`` is one vote).  With a named voter
        set installed the majority is per-set; during a joint window
        it is the LOWER of the two sets' floors — majorities of both
        C_old and C_new, the joint-consensus commit rule."""
        if not self.enabled:
            return self.db.zxid
        if self.voters is not None:
            floor = self._majority_floor(self.voters)
            if self.old_voters is not None:
                floor = min(floor,
                            self._majority_floor(self.old_voters))
            return floor
        pool = sorted([self.db.zxid] + list(self.acked.values()),
                      reverse=True)
        need = quorum_of(self.total)
        return pool[need - 1] if len(pool) >= need else 0

    def _floor_with_grant(self, grant, target: int) -> int:
        """The quorum floor with ``grant``'s vote counted virtually
        at ``target``: the forwarded-write RPC path — the calling
        follower's loop is parked inside the blocking RPC, but the
        response's own piggyback delivers the txn into its mirror
        before the client can see the ack, so its vote is guaranteed
        by construction, not awaited (awaiting it would deadlock a
        two-member ensemble into the degrade timeout per write).
        Under a named config the grant only counts when the granter
        is (still) a member of the set being tallied — a removed
        voter's virtual vote is fenced like its real ones."""
        if self.voters is not None:
            extra = (grant, target) if grant is not None else None
            floor = self._majority_floor(self.voters, extra)
            if self.old_voters is not None:
                floor = min(floor, self._majority_floor(
                    self.old_voters, extra))
            return floor
        pool = [self.db.zxid]
        if grant is not None:
            pool.append(target)
        pool += [z for v, z in self.acked.items() if v != grant]
        pool.sort(reverse=True)
        need = quorum_of(self.total)
        return pool[need - 1] if len(pool) >= need else 0

    def _advance(self) -> None:
        floor = self.quorum_zxid()
        if floor <= self.quorum_zxid_floor:
            return
        self.quorum_zxid_floor = floor
        now = time.monotonic()
        covered = [z for z in self._commit_t if z <= floor]
        for z in covered:
            dur_ms = (now - self._commit_t.pop(z)) * 1000.0
            if self._hist is not None:
                self._hist.observe(dur_ms)
        if self.trace is not None:
            self.trace.note('QUORUM_ACK', zxid=floor, kind='server',
                            batch=max(1, len(covered)))
        if floor >= self.db.zxid:
            # every committed txn is majority-held: corked acks leave
            self._release(degraded=False)
        for target, fut, grant in self._futs[:]:
            if not fut.done() and \
                    self._floor_with_grant(grant, target) >= target:
                fut.set_result(True)
        self._futs = [e for e in self._futs if not e[1].done()]

    # -- the ack gate (composed with the WAL by CommitBarrier) --

    def gate_flush(self, release) -> bool:
        """True when every committed txn is majority-held — the
        corked acks may leave.  Otherwise the flush stays corked,
        ``release`` re-flushes when the quorum floor reaches the
        current zxid, and the degrade timer bounds the wait."""
        if not self.enabled:
            return True
        if self.quorum_zxid() >= self.db.zxid \
                or self.db.zxid <= self.degraded_zxid:
            return True
        self._waiters.append(release)
        self._arm_timer()
        return False

    def sync_for_flush(self) -> None:
        """The synchronous barrier half is the WAL's alone: quorum
        acks arrive on the events channel THIS loop serves, so a hard
        flush (fault-injected delivery, connection close) cannot
        block on them — those frames leave fsynced-but-unconfirmed,
        exactly like a degraded release."""

    def _arm_timer(self) -> None:
        if self._timer is not None:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # no loop to deliver acks on either: degrade immediately —
            # and mark the floor BEFORE releasing, or the released
            # flush re-gates into this branch forever (the release IS
            # flush_now, which re-runs gate_flush synchronously)
            self.degraded_releases += 1
            self.degraded_zxid = self.db.zxid
            self._release(degraded=True)
            return
        self._timer = loop.call_later(self.wait_ms / 1000.0,
                                      self._degrade)

    def _degrade(self) -> None:
        self._timer = None
        if self._waiters and self.quorum_zxid() < self.db.zxid:
            self.degraded_releases += 1
            self.degraded_zxid = self.db.zxid
            log.warning('quorum wait degraded after %.0f ms (floor '
                        'zxid %d, leader zxid %d): acks leave '
                        'quorum-unconfirmed', self.wait_ms,
                        self.quorum_zxid_floor, self.db.zxid)
        self._release(degraded=True)

    def _release(self, degraded: bool) -> None:
        if self._timer is not None and not degraded:
            self._timer.cancel()
            self._timer = None
        waiters, self._waiters = self._waiters, []
        for release in waiters:
            try:
                release()
            except Exception:  # pragma: no cover - plane teardown
                log.exception('quorum gate release failed')

    async def wait(self, target_zxid: int,
                   timeout_s: float | None = None,
                   grant=None) -> bool:
        """Await the quorum floor reaching ``target_zxid`` (the
        forwarded-write RPC path): True on quorum, False on the
        degrade timeout.  ``grant`` is the calling follower's voter
        key, counted virtually at the target (see
        :meth:`_floor_with_grant`)."""
        if not self.enabled \
                or self._floor_with_grant(grant, target_zxid) \
                >= target_zxid:
            return True
        fut = asyncio.get_running_loop().create_future()
        self._futs.append((target_zxid, fut, grant))
        try:
            await asyncio.wait_for(
                fut, (timeout_s if timeout_s is not None
                      else self.wait_ms / 1000.0))
            return True
        except (asyncio.TimeoutError, TimeoutError):
            self.degraded_releases += 1
            return False
        finally:
            self._futs = [e for e in self._futs if e[1] is not fut]

    def close(self) -> None:
        # disable BEFORE releasing: a release re-enters gate_flush,
        # and a closed gate must gate nothing (re-registering here
        # would arm a fresh degrade timer on a gate being torn down)
        self.enabled = False
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._release(degraded=True)


class CommitBarrier:
    """The leader ack barrier: WAL group fsync AND quorum ack, taken
    together — a corked tick registers one release with each and
    flushes when both clear (io/sendplane.py ``barrier`` contract).
    Either half may be absent (WAL-less bench arms, quorum-disabled
    validator)."""

    __slots__ = ('wal', 'quorum')

    def __init__(self, wal, quorum):
        self.wal = wal
        self.quorum = quorum

    def gate_flush(self, release) -> bool:
        # call BOTH gates unconditionally: the fsync and the quorum
        # round-trip overlap instead of serializing
        wal_clear = self.wal is None or self.wal.gate_flush(release)
        q_clear = (self.quorum is None
                   or self.quorum.gate_flush(release))
        return wal_clear and q_clear

    def sync_for_flush(self) -> None:
        if self.wal is not None:
            self.wal.sync_for_flush()
        if self.quorum is not None:
            self.quorum.sync_for_flush()


class _FollowerHandle:
    """The leader-side stand-in for one remote follower in the
    database's replica registry.  ``applied`` is what the follower has
    ACKED as mirrored (never merely shipped): the truncation floor must
    stay at or below every index a control-channel piggyback may still
    be asked to serve from — a follower whose event loop is momentarily
    blocked must not have the log truncated out from under its next
    RPC.  ``shipped`` tracks the push cursor separately."""

    def __init__(self, token: str):
        self.token = token
        self.applied = 0
        self.shipped = 0
        #: True for a non-voting observer mirror (README "Read
        #: plane"): its acks still gate the truncation floor (the
        #: piggyback must always be able to serve from its mirror's
        #: end) but never count toward the quorum-commit majority.
        self.observer = False
        self.writer: asyncio.StreamWriter | None = None


class ReplicationService:
    """Leader-process side.  Owns no sockets of the ZK protocol — it
    serves follower processes, not clients; run a normal ``ZKServer``
    on the same ``db`` for the leader *member*."""

    def __init__(self, db: ZKDatabase, host: str = '127.0.0.1',
                 port: int = 0, total: int = 1, collector=None,
                 quorum: bool | None = None):
        self.db = db
        self.host = host
        self.port = port
        #: Quorum-commit (the leader's ack barrier): ``total`` is the
        #: ENSEMBLE membership (this leader included), so a
        #: standalone service (total=1) carries a disabled gate — the
        #: leader is its own majority.
        self.quorum = QuorumGate(db, total, enabled=quorum,
                                 collector=collector)
        self._server: asyncio.base_events.Server | None = None
        self._handles: dict[str, _FollowerHandle] = {}
        #: every open follower transport, severed on stop(): since
        #: Python 3.12.1 wait_closed() also waits for client handlers,
        #: which would otherwise loop forever on live channels (the
        #: same hazard ZKServer.stop() sorts around)
        self._writers: set[asyncio.StreamWriter] = set()
        self._subscribed = False
        #: Optional seeded FaultInjector (io/faults.py): drops
        #: leader->follower pushes to simulate an asymmetric partition
        #: (the follower's control channel keeps flowing, so forwarded
        #: writes still land while the event stream starves — the
        #: piggyback/ack machinery is what must absorb the gap).
        self.faults = None
        #: Deterministic partition windows, by follower token: while a
        #: token is in this set, EVERY push to it drops (the
        #: campaign-scheduled form of the asymmetric partition; the
        #: injector's ``drop_push`` is the probabilistic form).  Heal
        #: by discarding the token — recovery rides the control
        #: channel's piggyback, same as the probabilistic path.
        self.partitioned: set[str] = set()
        #: Fencing latch (server/election.py): set once this service
        #: learns a higher leadership epoch exists — an RPC stamped
        #: with a newer epoch, or the election layer deposing it
        #: directly.  A deposed leader's forwarded writes bounce with
        #: a typed EPOCH_FENCED error instead of being applied to (and
        #: acked from) a history the quorum has moved past.
        self.deposed = False

    @property
    def epoch(self) -> int:
        return getattr(self.db, 'epoch', 0)

    def depose(self, epoch: int | None = None) -> None:
        """Fence this service: a newer leader exists.  Forwarded
        writes from here on bounce with EPOCH_FENCED."""
        self.deposed = True
        log.warning('replication service deposed (epoch %d%s)',
                    self.epoch,
                    '' if epoch is None else ' -> %d' % (epoch,))

    async def start(self) -> 'ReplicationService':
        self._server = await asyncio.start_server(
            self._on_follower, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        if not self._subscribed:
            self.db.on('committed', self._push_commits)
            self.db.on('sessionExpired', self._push_expiry)
            self._subscribed = True
        log.info('replication service on %s:%d', self.host, self.port)
        return self

    async def stop(self) -> None:
        self.quorum.close()
        if self._server is not None:
            self._server.close()
            for w in list(self._writers):
                try:
                    w.close()
                except (ConnectionError, RuntimeError):
                    pass
            await self._server.wait_closed()
            self._server = None

    # -- pushes (events channel) --

    def _entries_from(self, have: int) -> tuple[int, list]:
        db = self.db
        assert have >= db.log_base, (have, db.log_base)
        return have, db.log[have - db.log_base:]

    def _push(self, handle: _FollowerHandle, msg,
              data: bytes | None = None) -> None:
        if handle.writer is None:
            return
        # Only steady-state pushes partition: the attach/snapshot
        # barrier is the join handshake — a partitioned joiner in real
        # ZK fails its sync and retries from scratch, which here would
        # just re-run connect(); dropping the handshake models nothing
        # the refusal faults don't already, and would turn every
        # campaign restart into a 10 s attach timeout.
        droppable = msg[0] in ('commit', 'session_expired')
        if droppable and handle.token in self.partitioned:
            return                   # scheduled partition window
        if droppable and self.faults is not None and \
                self.faults.drop_push(handle.token):
            # Asymmetric partition: this push is lost.  For 'commit'
            # pushes the shipped cursor still advances in
            # _push_commits, exactly like bytes lost in the network —
            # recovery rides the control channel's piggyback (acks
            # gate the truncation floor, so no entry is lost).
            return
        try:
            handle.writer.write(data if data is not None
                                else _dump(msg))
        except (ConnectionError, RuntimeError):
            pass

    def _push_commits(self) -> None:
        trace = getattr(self.db, 'trace', None)
        self.quorum.note_pushed(self.db.zxid)
        #: per-cursor encode memo: steady-state mirrors share one
        #: shipped position, so a commit's push bytes are pickled
        #: ONCE however many followers/observers subscribe — the read
        #: plane makes wide mirror fleets normal, and a per-handle
        #: pickle would bill every write O(mirrors) serializations
        memo: dict[int, bytes] = {}
        for h in self._handles.values():
            base, entries = self._entries_from(h.shipped)
            if entries:
                data = memo.get(base)
                if data is None:
                    data = memo[base] = _dump(
                        ('commit', base, entries, self.epoch))
                self._push(h, ('commit', base, entries, self.epoch),
                           data=data)
                h.shipped = base + len(entries)
                if trace is not None:
                    # one push span per follower, keyed by the newest
                    # zxid shipped — the leader-side replication leg
                    # of the merged timeline
                    trace.note('REPL_PUSH',
                               zxid=entry_zxid(entries[-1]),
                               kind='server', batch=len(entries),
                               detail=h.token[:8])

    def _push_expiry(self, session_id: int) -> None:
        for h in self._handles.values():
            self._push(h, ('session_expired', session_id, self.epoch))

    # -- per-follower connections --

    async def _on_follower(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        try:
            await self._serve_follower(reader, writer)
        finally:
            self._writers.discard(writer)

    async def _serve_follower(self, reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter) -> None:
        try:
            hello = await _read_msg(reader)
        except (asyncio.IncompleteReadError, ConnectionError):
            writer.close()
            return
        kind, token = hello[0], hello[1]
        # a follower that recovered its tree from its own WAL
        # (server/persist.py) announces the zxid it holds; None for
        # fresh joiners and pre-durability hellos
        have_zxid = hello[2] if len(hello) > 2 else None
        # a non-voting observer stamps its hello (both channels):
        # its acks and forwarded writes must never help assemble a
        # quorum-commit majority
        is_observer = len(hello) > 3 and hello[3] == 'observer'
        if kind == 'events':
            h = self._handles.get(token)
            if h is None:
                h = _FollowerHandle(token)
                h.observer = is_observer
                h.writer = writer
                try:
                    self.db.attach_replica(h)
                except ValueError:
                    # a late joiner (a follower restarted — or first
                    # started — after history began).  A follower that
                    # recovered from disk rejoins with its recovered
                    # zxid as the catch-up base when the retained log
                    # still covers it — shipped only the tail, no
                    # image; otherwise (and for fresh joiners) it is
                    # bootstrapped from a snapshot, real ZK's follower
                    # resync.  The log before replication began was
                    # never retained; the tree image carries its
                    # effects.
                    pos = None
                    if have_zxid is not None:
                        pos = self.db.attach_replica_resync(
                            h, have_zxid)
                        if pos is not None:
                            h.applied = h.shipped = pos
                            self._push(h, ('resync', pos, self.epoch))
                            log.info(
                                'follower %s rejoined by WAL resync '
                                'at log index %d (recovered zxid %d, '
                                'leader zxid %d)', token, pos,
                                have_zxid, self.db.zxid)
                    if pos is None:
                        pos = self.db.attach_replica_at_tail(h)
                        h.applied = h.shipped = pos
                        # the image carries the SESSION TABLE too:
                        # session records before the bootstrap
                        # position were never retained, and a
                        # promoted ex-follower must not expire every
                        # client (store.py session_snapshot)
                        self._push(h, ('snapshot', self.db.snapshot(),
                                       pos, self.epoch,
                                       self.db.session_snapshot(),
                                       self.db.config_snapshot()))
                        log.info('follower %s joined late: snapshot '
                                 'at log index %d (zxid %d)', token,
                                 pos, self.db.zxid)
                self._handles[token] = h
            else:
                h.writer = writer
            # the follower's connect() blocks until this lands: a
            # commit racing the hello would otherwise slip between
            # "connected" and "attached" and never be logged.  The
            # membership config rides along: the zero-history attach
            # path ships no snapshot, and a follower must still
            # learn the ensemble shape it joined
            self._push(h, ('attached', self.epoch,
                           self.db.config_snapshot()))
            # ship anything committed before this follower connected
            self._push_commits()
            try:
                # the follower acks mirrored indices on this channel;
                # acks are what advance the truncation floor, and the
                # piggybacked (applied_zxid, epoch) pair is what
                # advances the quorum-commit floor
                while True:
                    msg = await _read_msg(reader)
                    if msg[0] == 'ack':
                        h.applied = max(h.applied, msg[1])
                        if len(msg) > 2 and not h.observer:
                            # observer acks advance the truncation
                            # floor (h.applied above) but never the
                            # quorum-commit majority
                            self.quorum.note_ack(
                                h.token, msg[2],
                                msg[3] if len(msg) > 3 else None)
            except (asyncio.IncompleteReadError, ConnectionError):
                pass                         # EOF = follower died
            finally:
                self._detach(h)
        elif kind == 'control':
            await self._serve_control(reader, writer, token,
                                      is_observer=is_observer)
        else:  # pragma: no cover - only this module speaks the protocol
            writer.close()

    def _detach(self, h: _FollowerHandle) -> None:
        self._handles.pop(h.token, None)
        self.quorum.forget(h.token)
        if h in self.db._replicas:
            self.db._replicas.remove(h)
        if h.writer is not None:
            h.writer.close()
            h.writer = None
        log.info('follower %s detached', h.token)

    async def _serve_control(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter,
                             token: str | None = None,
                             is_observer: bool = False) -> None:
        db = self.db
        try:
            while True:
                msg = await _read_msg(reader)
                op = msg[0]
                if op == 'touch':
                    sess = db.sessions.get(msg[1])
                    if sess is not None and not sess.expired \
                            and not sess.closed:
                        db.touch_session(sess)
                    continue
                assert op == 'rpc', op
                _, seq, method, args, have = msg[:5]
                rpc_epoch = msg[5] if len(msg) > 5 else None
                if rpc_epoch is not None and rpc_epoch > self.epoch:
                    # the caller has seen a newer leader than this
                    # service: it IS deposed, whatever it believed
                    self.depose(rpc_epoch)
                if (self.deposed or (rpc_epoch is not None
                                     and rpc_epoch < self.epoch)) \
                        and method in ('create', 'delete', 'set_data',
                                       'multi'):
                    # epoch fence: a deposed leader must not apply —
                    # or ack — a forwarded write, and a stale-epoch
                    # follower's write must bounce until it rejoins
                    # the current epoch.  Typed, never silent.
                    status, payload = 'err', 'EPOCH_FENCED'
                else:
                    pre_zxid = db.zxid
                    status, payload = self._dispatch(method, args)
                    if db.wal is not None:
                        # logged-before-ack across processes too: a
                        # forwarded write's RPC response is its ack
                        db.wal.sync_for_flush()
                    if status == 'ok' and db.zxid > pre_zxid \
                            and method in (
                            'create', 'delete', 'set_data', 'multi'):
                        # the zxid guard skips writes that committed
                        # nothing (a rejected multi reports per-op
                        # errors under status 'ok'; a check-only
                        # batch consumes no zxid) — they must not
                        # stall on unrelated in-flight writes' quorum
                        # quorum-before-ack: the response leaves only
                        # once a majority holds the txn.  The CALLING
                        # follower's vote is granted virtually — this
                        # very response's piggyback delivers the txn
                        # into its mirror before the client can see
                        # the ack (its loop is parked in the blocking
                        # RPC, so awaiting its real ack would
                        # deadlock).  An OBSERVER caller gets no
                        # virtual grant: its mirror is outside the
                        # voter set, so the majority must assemble
                        # from real voter acks alone.  Bounded:
                        # degrades like the send-plane gate.
                        await self.quorum.wait(
                            db.zxid,
                            grant=None if is_observer else token)
                base, entries = self._entries_from(have)
                writer.write(_dump(
                    ('res', seq, status, payload, base, entries,
                     self.epoch)))
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    def _dispatch(self, method: str, args: tuple):
        db = self.db
        try:
            if method == 'create':
                path, data, acl, flags, sid = args
                return 'ok', db.create(path, data, acl,
                                       CreateFlag(flags),
                                       db.sessions.get(sid))
            if method == 'delete':
                db.delete(*args)
                return 'ok', None
            if method == 'set_data':
                return 'ok', db.set_data(*args)
            if method == 'multi':
                ops, sid = args
                return 'ok', db.multi(ops, db.sessions.get(sid))
            if method == 'create_session':
                sess = db.create_session(args[0])
                return 'ok', (sess.id, sess.passwd, sess.timeout)
            if method == 'resume_session':
                sess = db.resume_session(*args)
                if sess is None:
                    return 'ok', None
                return 'ok', (sess.id, sess.passwd, sess.timeout)
            if method == 'close_session':
                db.close_session(args[0])
                return 'ok', None
            if method == 'sync_barrier':
                return 'ok', None    # the piggybacked entries ARE the
                                     # barrier: up through db.log_end()
            return 'exc', 'unknown rpc %r' % (method,)
        except ZKOpError as e:
            return 'err', e.code
        except Exception as e:  # pragma: no cover - leader-side bug
            log.exception('rpc %s failed', method)
            return 'exc', repr(e)


class RemoteLeader(EventEmitter):
    """Follower-process side: the ``db``-shaped object a ``ZKServer``
    forwards leader operations through, plus the commit-log mirror its
    :class:`RemoteReplicaStore` replays.

    Emits ``committed`` (mirror grew) and ``sessionExpired(sid)`` —
    the two ``ZKDatabase`` events the server stack subscribes to."""

    def __init__(self, host: str, port: int,
                 have_zxid: int | None = None, epoch: int = 0,
                 observer: bool = False):
        super().__init__()
        self.host = host
        self.port = port
        #: Non-voting observer mirror (README "Read plane"): both
        #: hellos are stamped so the leader excludes this mirror's
        #: acks and forwarded writes from quorum-commit majorities.
        self.observer = observer
        #: newest mirror index actually ACKED to the leader: observer
        #: acks batch (see OBS_ACK_BATCH in :meth:`_ingest`)
        self._acked_sent = 0
        import uuid
        self._token = uuid.uuid4().hex
        #: the zxid this follower recovered from its own WAL
        #: (server/persist.py), announced in the events hello so the
        #: leader can ship only the tail instead of a snapshot
        self.have_zxid = have_zxid
        #: the newest leadership epoch this follower has accepted
        #: (recovered from its mirror WAL, then adopted upward from
        #: the stamp on every push / RPC response).  Pushes stamped
        #: with a LOWER epoch are rejected — the fencing half of
        #: server/election.py — and counted in ``stale_pushes``.
        self.epoch = epoch
        self.stale_pushes = 0
        #: invoked exactly once when the events channel dies without
        #: ``close()`` — the follower's leader-loss signal (push-
        #: channel EOF), what re-enters the election loop
        self.on_leader_lost = None
        self._lost_noted = False
        self._closing = False
        #: the commit-log mirror (never truncated: one local replica)
        self.log: list = []
        self.log_base = 0
        self.sessions: dict[int, ZKServerSession] = {}
        #: replicated membership config (store.py config_snapshot
        #: form): seeded by the bootstrap image, then maintained by
        #: the reconfig records the mirror replays — a promoted
        #: ex-follower inherits the config, including an in-progress
        #: joint window it must finish (server/election.py run_member)
        self.config: dict | None = None
        #: optional mirror write-ahead log: every entry that lands in
        #: the mirror is appended (durability for the follower's own
        #: restart; the worker wires this, tests/process_member_worker)
        self.wal = None
        #: set when the leader bootstrapped this (late-joining)
        #: follower from a snapshot: (image, absolute log index) that
        #: RemoteReplicaStore installs before replaying the tail
        self._snapshot: tuple[dict, int] | None = None
        #: set when the leader accepted ``have_zxid`` as the catch-up
        #: base ('resync'): the recovered tree stands, only the tail
        #: is shipped
        self.resynced = False
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()
        #: serializes mirror growth: in the follower process both
        #: channels run on one event loop, but test harnesses (and any
        #: future off-loop caller) may drive the blocking control
        #: channel from another thread, and a racy double-append would
        #: shift every later batch's slice indices
        self._mirror_lock = threading.Lock()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._seq = 0
        self._events_task: asyncio.Task | None = None
        #: kept referenced: a dropped StreamWriter closes its transport
        #: and the leader would see EOF and detach this follower
        self._events_writer: asyncio.StreamWriter | None = None

    @property
    def token(self) -> str:
        """This follower's channel-pairing token — the key the
        leader-side partition controls (``ReplicationService.
        partitioned``, ``FaultInjector.drop_push``) select it by."""
        return self._token

    # -- ReplicaStore's leader surface --

    def log_end(self) -> int:
        return self.log_base + len(self.log)

    def attach_replica(self, replica) -> None:
        # Any time is fine here, unlike ZKDatabase.attach_replica: a
        # replica either replays the never-truncated mirror from 0 or
        # installs the leader's snapshot and starts at log_base
        # (RemoteReplicaStore.__init__ picks per self._snapshot).
        pass

    async def connect(self) -> 'RemoteLeader':
        self._loop = asyncio.get_running_loop()
        # the control-channel dial can hang on a partitioned peer —
        # it must park an executor thread, not the loop every other
        # session of this member is served from (the loop-blocking
        # checker surfaced this one)
        # bounded dial: a leader partitioned right after election
        # must fail this connect within the attach window, not after
        # the kernel's multi-minute SYN retry — the election loop
        # needs the OSError promptly to try again
        self._sock = await self._loop.run_in_executor(
            None, socket.create_connection,
            (self.host, self.port), 10)
        self._sock.settimeout(None)     # RPCs keep blocking semantics
        role = 'observer' if self.observer else None
        self._sock.sendall(_dump(('control', self._token, None,
                                  role)))
        reader, writer = await asyncio.open_connection(
            self.host, self.port)
        writer.write(_dump(('events', self._token, self.have_zxid,
                            role)))
        await writer.drain()
        self._events_writer = writer
        self._attached = asyncio.get_running_loop().create_future()
        self._events_task = asyncio.get_running_loop().create_task(
            self._consume_events(reader))
        # barrier: until the leader confirms the attach (snapshot
        # included for a late joiner), a commit could race this
        # follower into a silent gap before its handle exists
        try:
            await asyncio.wait_for(self._attached, timeout=10)
        except BaseException:
            self.close()
            raise
        return self

    def close(self) -> None:
        self._closing = True
        if self._events_task is not None:
            self._events_task.cancel()
            self._events_task = None
        if self._events_writer is not None:
            self._events_writer.close()
            self._events_writer = None
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def _adopt_epoch(self, epoch: int | None) -> bool:
        """Adopt a push's epoch stamp.  Returns False when the push is
        STALE (stamped below the epoch this follower has already
        accepted) and must be rejected — the fencing rule that keeps a
        deposed leader's late pushes out of the mirror."""
        if epoch is None:
            return True
        if epoch < self.epoch:
            self.stale_pushes += 1
            log.warning('rejecting push from stale epoch %d '
                        '(accepted epoch is %d)', epoch, self.epoch)
            return False
        if epoch > self.epoch:
            with self._mirror_lock:
                if epoch > self.epoch:
                    self.epoch = epoch
                    if self.wal is not None:
                        # persist the fence — and fsync it, same rule
                        # as bump_epoch: a restarted follower must
                        # come back knowing the epoch it had
                        # accepted, or a stale leader could re-seed
                        # it.  Epoch changes are rare; the blocking
                        # sync never rides the per-push hot path.
                        self.wal.append(('epoch', epoch,
                                         self.wal.last_zxid))
                        self.wal.sync_for_flush()
        return True

    def _note_leader_lost(self) -> None:
        if self._lost_noted or self._closing:
            return
        self._lost_noted = True
        cb = self.on_leader_lost
        if cb is not None:
            try:
                cb()
            except Exception:  # pragma: no cover - observer bug
                log.exception('on_leader_lost callback failed')

    async def _consume_events(self, reader: asyncio.StreamReader):
        try:
            while True:
                msg = await _read_msg(reader)
                if msg[0] == 'commit':
                    if not self._adopt_epoch(
                            msg[3] if len(msg) > 3 else None):
                        continue       # fenced: a stale leader's push
                    self._ingest(msg[1], msg[2])
                    self.emit('committed')
                elif msg[0] == 'session_expired':
                    self._adopt_epoch(msg[2] if len(msg) > 2 else None)
                    sess = self.sessions.get(msg[1])
                    if sess is not None:
                        sess.expired = True
                    self.emit('sessionExpired', msg[1])
                elif msg[0] == 'snapshot':
                    # always precedes 'attached' on this ordered
                    # socket; the mirror starts at the image's index
                    self._adopt_epoch(msg[3] if len(msg) > 3 else None)
                    with self._mirror_lock:
                        assert not self.log, 'snapshot after entries'
                        self._snapshot = (msg[1], msg[2])
                        self.log_base = msg[2]
                    self.seed_sessions(msg[4] if len(msg) > 4 else {})
                    if len(msg) > 5 and msg[5] is not None:
                        self.config = dict(msg[5])
                elif msg[0] == 'resync':
                    # the leader accepted have_zxid as the catch-up
                    # base: no image — the recovered tree stands and
                    # the mirror starts at the leader's matching index
                    self._adopt_epoch(msg[2] if len(msg) > 2 else None)
                    with self._mirror_lock:
                        assert not self.log, 'resync after entries'
                        self.resynced = True
                        self.log_base = msg[1]
                elif msg[0] == 'attached':
                    self._adopt_epoch(msg[1] if len(msg) > 1 else None)
                    if len(msg) > 2 and msg[2] is not None \
                            and self.config is None:
                        # don't regress a config a later reconfig
                        # record already advanced past this
                        # handshake's stamp
                        self.config = dict(msg[2])
                    if not self._attached.done():
                        self._attached.set_result(True)
        except asyncio.CancelledError:
            pass
        except (asyncio.IncompleteReadError, ConnectionError):
            # push-channel EOF: the leader died (or severed us) — the
            # follower's election trigger (server/election.py)
            self._note_leader_lost()

    def _ingest(self, base: int, entries: list) -> None:
        """Merge a batch of log entries starting at absolute index
        ``base`` into the mirror (entries can arrive on both channels;
        overlap is dropped under the mirror lock, gaps are impossible
        on ordered sockets from one leader loop).  Growth is acked to
        the leader — acks, not shipments, advance its truncation
        floor, so the control channel's piggyback can always serve
        from this mirror's end."""
        with self._mirror_lock:
            end = self.log_end()
            if base > end:
                # a gap: an earlier push was dropped — a scheduled
                # partition window or a stale-epoch rejection
                # (_adopt_epoch).  A gapped batch cannot be merged;
                # recovery rides the control channel's piggyback,
                # which always serves from this mirror's end.
                return
            tail = entries[end - base:]
            if tail:
                self.log.extend(tail)
                if self.wal is not None:
                    # mirror durability: the follower's own WAL logs
                    # what it has mirrored, so a SIGKILLed follower
                    # restarts from disk and rejoins with have_zxid
                    # (in the worker both channels share one loop, so
                    # appends are loop-serialized like the leader's)
                    for e in tail:
                        self.wal.append(e)
            acked = self.log_end()
            acked_zxid = entry_zxid(self.log[-1]) if self.log else 0
        if tail and self.observer \
                and acked - self._acked_sent < self.OBS_ACK_BATCH:
            # observer acks gate ONLY the leader's log-truncation
            # floor (never a quorum), so they batch: one ack per
            # OBS_ACK_BATCH ingested entries instead of one per
            # commit — at read-plane fleet widths, per-commit acks
            # from every observer made the leader process O(mirrors)
            # messages per write.  The retained-log cost is bounded
            # (< OBS_ACK_BATCH entries per observer).
            return
        if tail and self._events_writer is not None:
            self._acked_sent = acked
            # the ack rides the events transport, which belongs to the
            # loop: schedule the write there when called off-loop.
            # The piggybacked (applied_zxid, epoch) pair is the
            # quorum-commit vote: the leader's ack barrier releases
            # once a majority of mirrors has ingested the txn, and an
            # ack stamped with a stale epoch is fenced out.
            data = _dump(('ack', acked, acked_zxid, self.epoch))

            def send():
                try:
                    self._events_writer.write(data)
                except (AttributeError, ConnectionError, RuntimeError):
                    pass                  # closed mid-shutdown
            try:
                on_loop = asyncio.get_running_loop() is self._loop
            except RuntimeError:
                on_loop = False           # no loop on this thread
            if on_loop:
                send()
            elif self._loop is not None:
                try:
                    self._loop.call_soon_threadsafe(send)
                except RuntimeError:
                    pass                  # loop closed


    # -- control-channel RPC --

    def _rpc(self, method: str, *args):
        try:
            with self._lock:
                if self._sock is None:
                    raise ZKLeaderLostError('not connected')
                self._seq += 1
                seq = self._seq
                self._sock.sendall(_dump(
                    ('rpc', seq, method, args, self.log_end(),
                     self.epoch)))
                res = _recv_msg(self._sock)
        except (ConnectionError, OSError) as e:
            # the leader process died (or the OS severed the control
            # channel) with this RPC in flight: its outcome is
            # unknown.  Surface the typed, outcome-unknown error the
            # client-side ambiguity accounting classifies — never a
            # raw EOF that tears the serving connection down.
            self._note_leader_lost()
            raise ZKLeaderLostError(str(e)) from e
        tag, rseq, status, payload, base, entries = res[:6]
        assert tag == 'res' and rseq == seq, res
        self._adopt_epoch(res[6] if len(res) > 6 else None)
        self._ingest(base, entries)
        if entries:
            self.emit('committed')
        if status == 'err':
            raise ZKOpError(payload)
        if status == 'exc':
            raise RuntimeError('leader rpc failed: %s' % (payload,))
        return payload

    # -- the ZKDatabase surface ServerConnection uses --

    def create(self, path, data, acl, flags, session=None):
        sid = session.id if session is not None else 0
        return self._rpc('create', path, data, acl, int(flags), sid)

    def delete(self, path, version):
        return self._rpc('delete', path, version)

    def set_data(self, path, data, version):
        return self._rpc('set_data', path, data, version)

    def multi(self, ops, session=None):
        """Forward one all-or-nothing MULTI batch; the leader applies
        it as ONE transaction (store.py ``ZKDatabase.multi``) and the
        RPC piggyback delivers the whole ('multi', subs) entry into
        this mirror before the ack, like any forwarded write."""
        sid = session.id if session is not None else 0
        return self._rpc('multi', list(ops), sid)

    def sync_barrier(self) -> None:
        """Round-trip to the leader; on return the mirror holds every
        transaction the leader had committed when the RPC arrived."""
        self._rpc('sync_barrier')

    def seed_sessions(self, table: dict) -> None:
        """Seed the mirror's session table from a durable form
        (``{sid: (passwd, timeout)}``): the leader's bootstrap image,
        or this member's own recovered table on rejoin.  Existing
        handles win — they may already carry lifecycle state."""
        for sid, (passwd, timeout) in table.items():
            if sid not in self.sessions:
                self.sessions[sid] = ZKServerSession(
                    id=sid, passwd=passwd, timeout=timeout)

    def session_snapshot(self) -> dict:
        """The mirror's session table in durable form — what a
        promoted ex-follower seats into its new leader database."""
        return durable_sessions(self.sessions)

    def _session(self, sid: int, passwd: bytes,
                 timeout: int) -> ZKServerSession:
        sess = self.sessions.get(sid)
        if sess is None:
            sess = self.sessions[sid] = ZKServerSession(
                id=sid, passwd=passwd, timeout=timeout)
        return sess

    def create_session(self, timeout: int) -> ZKServerSession:
        sid, passwd, timeout = self._rpc('create_session', timeout)
        return self._session(sid, passwd, timeout)

    def resume_session(self, session_id: int,
                       passwd: bytes) -> ZKServerSession | None:
        res = self._rpc('resume_session', session_id, passwd)
        if res is None:
            return None
        return self._session(*res)

    #: Floor on the touch-forward interval, seconds: even a tiny
    #: session timeout must not turn every served request into a
    #: leader RPC.
    TOUCH_MIN_S = 0.1

    #: Observer ack batching (:meth:`_ingest`): one truncation-floor
    #: ack per this many ingested entries.  Voting followers always
    #: ack per batch — their piggybacked zxid IS the quorum vote.
    OBS_ACK_BATCH = 64

    def touch_session(self, sess: ZKServerSession) -> None:
        # Fire-and-forget (expiry timers live in the leader process)
        # and RATE-LIMITED to a quarter of the session timeout — real
        # ZK's learner forwards session activity at ping cadence, not
        # per request.  Without the limit, every read served by a
        # follower/observer costs the leader one control-channel
        # message plus an expiry-timer reset: at read-plane scale the
        # leader becomes the READ path's bottleneck even though it
        # serves none of the reads.
        now = time.monotonic()
        if now - sess.last_touch_fwd < max(
                self.TOUCH_MIN_S, sess.timeout / 4000.0):
            return
        sess.last_touch_fwd = now
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.sendall(_dump(('touch', sess.id)))
                except (ConnectionError, OSError):
                    self._note_leader_lost()

    def close_session(self, session_id: int) -> None:
        self._rpc('close_session', session_id)
        sess = self.sessions.get(session_id)
        if sess is not None:
            sess.closed = True


class RemoteReplicaStore(ReplicaStore):
    """A follower's replica over a :class:`RemoteLeader` mirror.  Two
    semantic differences from the in-process replica:

    - a late joiner installs the leader's snapshot and replays only
      the tail (the mirror's ``log_base`` is the image's index);
    - the SYNC op's barrier must first *fetch* — everything the
      leader has committed is the sync point, not everything the
      mirror happens to hold.  Plain ``catch_up`` (the
      read-your-own-write step after a forwarded write) stays local:
      the write RPC's piggyback already delivered the mirror through
      the write, and a second blocking round-trip per write would
      stall the member's whole event loop."""

    #: Optional hook fired with each reconfig record's config dict as
    #: it applies — run_member repoints this follower's election
    #: total from it, so a later ballot counts quorums against the
    #: membership the leader last committed, not the spawn shape.
    on_config_applied = None

    def _apply_session(self, entry: tuple) -> None:
        """Session control records replicate the leader's session
        table into THIS follower's mirror handle — what keeps every
        session alive across an OS-process leader failover: the
        promoted member seats ``leader.sessions`` into its new
        database instead of expiring every client."""
        sessions = self.leader.sessions
        if entry[0] == 'session':
            _, sid, passwd, timeout, _zxid = entry
            if sid not in sessions:
                sessions[sid] = ZKServerSession(
                    id=sid, passwd=passwd, timeout=timeout)
        else:
            sess = sessions.get(entry[1])
            if sess is not None:
                if entry[3] == 'expire':
                    sess.expired = True
                else:
                    sess.closed = True

    def _apply_reconfig(self, entry: tuple) -> None:
        """Reconfig control records replicate the leader's membership
        config into THIS follower's mirror handle — a promoted member
        inherits it, joint window included (the run_member lead path
        finishes an in-progress reconfig it recovers this way)."""
        _, ver, phase, old_v, new_v, obs, _zxid = entry
        cfg = {
            'version': ver, 'phase': phase, 'voters': tuple(new_v),
            'old_voters': (tuple(old_v) if phase == 'joint'
                           else None),
            'observers': tuple(obs)}
        self.leader.config = cfg
        hook = self.on_config_applied
        if hook is not None:
            hook(cfg)

    def __init__(self, leader: RemoteLeader, lag: float | None = 0.0,
                 recovered: dict | None = None):
        super().__init__(leader, lag=lag)
        if leader._snapshot is not None:
            snap, pos = leader._snapshot
            leader._snapshot = None     # release the image: installed
            self.install(snap)          # state must not be pinned (or
            self.applied = pos          # re-installed) afterwards
        elif recovered is not None and leader.resynced:
            # restart-from-disk: the tree recovered from this
            # follower's own WAL is the catch-up base — the leader
            # shipped no image, only the tail past recovered['zxid']
            self.install(recovered)
            self.applied = leader.log_base
        if self.lag is not None and self.lag <= 0:
            # entries can land in the mirror between the snapshot (or
            # plain attach) and this construction; _on_commit only
            # fires on FUTURE pushes, so apply the backlog now or a
            # lag=0 replica could serve stale reads until the next
            # unrelated write
            self.catch_up()

    def sync_flush(self) -> None:
        self.leader.sync_barrier()
        self.catch_up()
