"""In-process ZooKeeper server: data model, wire server, ensemble
simulation, and cross-process member replication (the rebuild's
replacement for the reference's JVM-spawning test harness,
test/zkserver.js)."""

from .persist import (  # noqa: F401
    WriteAheadLog,
    attach_wal,
    open_wal_database,
    recover_state,
    scan_dir,
)
from .replication import (  # noqa: F401
    RemoteLeader,
    RemoteReplicaStore,
    ReplicationService,
)
from .server import ServerConnection, ZKEnsemble, ZKServer  # noqa: F401
from .watchtable import WatchTable, watchtable_default  # noqa: F401
from .store import (  # noqa: F401
    NodeTree,
    ReplicaStore,
    ZKDatabase,
    ZKOpError,
    ZKServerSession,
    Znode,
)
