"""In-process ZooKeeper server: data model, wire server, and ensemble
simulation (the rebuild's replacement for the reference's JVM-spawning
test harness, test/zkserver.js)."""

from .server import ServerConnection, ZKEnsemble, ZKServer  # noqa: F401
from .store import (  # noqa: F401
    NodeTree,
    ReplicaStore,
    ZKDatabase,
    ZKOpError,
    ZKServerSession,
    Znode,
)
