"""The durability plane: write-ahead log, fuzzy snapshots, recovery.

Every byte of ensemble state used to be RAM: ``NodeTree.snapshot()``
existed only to bootstrap late-joining replicas, and a killed member
recovered solely by resyncing from a *live* leader — kill the whole
ensemble and every acked write was gone.  This module adds the disk
half of real ZooKeeper's guarantee: a length-prefixed, CRC32C-framed
**write-ahead log** of committed transactions, **fuzzy snapshots** of
the znode tree stamped with their log position, and **recovery** that
loads the newest valid snapshot and replays the log tail — tolerating
a torn final record, the normal signature of dying mid-write.

Group commit (the fsync policy) reuses the shape the outbound plane
proved out (io/sendplane.py, PROFILE.md "Encode side"): one fsync per
busy event-loop tick instead of one per append, with an ordering
barrier so durability still *precedes* every ack:

- ``sync='always'`` — flush + fsync on every append (one syscall pair
  per committed txn; the strict, slow policy);
- ``sync='tick'`` (default) — appends of one event-loop iteration
  share ONE group fsync that runs on an executor thread (real ZK's
  sync-thread shape: the loop keeps serving reads and later writes
  while the device syncs), and the server send-plane carries the WAL
  as its ``barrier``: corked acks stay corked — still in order —
  until the fsync covering their txns completes, so **no ack byte
  reaches the transport before its txn is on disk** while the loop
  never blocks on the device;
- ``sync='never'`` — OS-buffered only (bench baseline / explicit
  opt-out; a crash may lose acked writes, the guarantee matrix in
  README "Durability" says so).

Snapshots are *fuzzy* in the ZooKeeper sense: applies continue while
the image is persisted.  The stamp (``next log index``, ``tree.zxid``)
and the pickle of the node map are captured synchronously in one tick
— so replay needs no idempotence — and the file write + fsync +
atomic rename happen off the hot path; segment truncation is anchored
to the newest *durable* snapshot only.  Record bodies ride the jute
primitive codec (`protocol/jute.py`) as the validating spec tier with
a single-pass struct-packed fast tier in front, mirroring
``protocol/fastencode.py``; the two are A/B-tested byte-identical
(tests/test_wal.py).

Wire format, one record: ``>I length | >I crc32c(body) | body``.
Records use CRC32C (Castagnoli — the checksum real ZK's and most
storage formats' tooling expects); snapshot payloads, megabytes not
tens of bytes, are covered by zlib.crc32 for C-speed — the goal there
is bit-flip detection, and a pure-Python CRC32C over a large tree
would cost more than the pickle itself.

Knobs: ``ZKServer(durability=, wal_dir=)``, ``ZKSTREAM_WAL_DIR``
(ambient default dir), ``ZKSTREAM_NO_WAL=1`` (global kill switch).
``python -m zkstream_tpu wal DIR`` dumps/verifies a log directory.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import os
import pickle
import struct
import time
import zlib

from ..protocol.jute import JuteReader, JuteWriter
from ..protocol.records import ACL, Id
from ..utils.aio import ambient_loop

log = logging.getLogger('zkstream_tpu.server.persist')

# ---------------------------------------------------------------------
# CRC32C (Castagnoli), software table.  Small-record checksumming only;
# snapshot payloads use zlib.crc32 (see module docstring).
# ---------------------------------------------------------------------

_CRC32C_POLY = 0x82F63B78


def _crc32c_table() -> tuple:
    out = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
        out.append(c)
    return tuple(out)


_CRC_TABLE = _crc32c_table()


def software_crc32c(data: bytes, crc: int = 0) -> int:
    """The spec tier: pure-Python table walk (always present).
    Known-answer: ``crc32c(b'123456789') == 0xE3069283``."""
    c = crc ^ 0xFFFFFFFF
    tbl = _CRC_TABLE
    for b in data:
        c = tbl[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


_crc_impl = None


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC32C over ``data`` (standard reflected form; chainable via
    ``crc``).  Tiered like the wire codec: the C extension's
    table walk when built (~60x — it checksums every appended record
    on the commit hot path), the Python spec otherwise; A/B-tested
    equal in tests/test_wal.py.  The binding resolves once, at first
    use, through the same already-built-artifact rule the frame
    scanner uses (utils/native.get_ext — never a blocking build)."""
    global _crc_impl
    if _crc_impl is None:
        impl = software_crc32c
        try:
            from ..utils import native
            ext = native.get_ext()
            if ext is not None and hasattr(ext, 'crc32c'):
                impl = ext.crc32c
        except Exception:  # pragma: no cover - packaging-broken ext
            pass
        _crc_impl = impl
    return _crc_impl(data, crc)


# ---------------------------------------------------------------------
# Txn record body codec: fast single-pass tier + jute spec tier.
# ---------------------------------------------------------------------

#: Tag 4 ('epoch') is a *control* record — a leadership-epoch bump
#: (server/election.py), logged for recovery but never applied to the
#: tree and never entered into the replication log.  Tags 5/6
#: ('session' / 'session_close') are the durable-session records:
#: session lifecycle rides the WAL (and the replication log — a
#: follower's mirror must carry the table for failover) but never
#: touches the tree; they carry the zxid CURRENT at the edge, consume
#: none, and recovery filters them by log index, not zxid.  Tag 7
#: ('multi') is one all-or-nothing transaction: every sub-entry in
#: ONE CRC-framed record, so a torn multi replays atomically or not
#: at all.  Tag 8 ('reconfig') is the membership CONTROL record
#: (server/store.py ``propose_reconfig``/``commit_reconfig``): a
#: config change rides the WAL and the replication log — phase
#: 'joint' installs C_old+C_new (quorum-commit and elections need
#: majorities of BOTH voter sets), phase 'final' commits C_new alone
#: — it consumes a zxid (the joint window is bounded by sequenced,
#: committed records), and recovery filters it by LOG INDEX like the
#: session records, so an in-progress reconfig survives a
#: full-ensemble SIGKILL and the promoted successor can finish it.
_TAGS = {'create': 1, 'delete': 2, 'set_data': 3, 'epoch': 4,
         'session': 5, 'session_close': 6, 'multi': 7, 'reconfig': 8}
_OPS = {v: k for k, v in _TAGS.items()}

#: ('reconfig', version, phase, old_voters, new_voters, observers,
#: zxid) phase byte values.
_RECONFIG_PHASES = {'joint': 0, 'final': 1}
_RECONFIG_NAMES = {v: k for k, v in _RECONFIG_PHASES.items()}

#: ('session_close', sid, zxid, reason) reason byte values.
_CLOSE_REASONS = {'close': 0, 'expire': 1}
_CLOSE_NAMES = {v: k for k, v in _CLOSE_REASONS.items()}

_REC_HDR = struct.Struct('>II')       # length, crc32c(body)
_I = struct.Struct('>i')
_Q3 = struct.Struct('>qqq')
_Q2 = struct.Struct('>qq')

#: Sanity cap on one record body (a txn's data is bounded by the wire
#: MAX_PACKET of 16 MiB; anything bigger is corruption, not data).
MAX_RECORD = 64 * 1024 * 1024

MAGIC_SEGMENT = b'ZKSWAL1\n'
#: Snapshot format 3 puts the SESSION TABLE into the image (payload
#: becomes ``{'nodes': ..., 'sessions': {sid: (passwd, timeout)}}``)
#: so ephemerals survive a full-ensemble restart inside the session
#: timeout.  Format 2 added the leadership epoch to the stamp (a
#: snapshot that anchors truncation may be the only survivor of the
#: epoch record it covers).  OLDER FORMATS STAY READABLE (epoch 0 /
#: empty session table): truncation may already have deleted the
#: segments under an existing snapshot, so rejecting it would orphan
#: the acked writes it covers.
MAGIC_SNAPSHOT = b'ZKSSNP3\n'
MAGIC_SNAPSHOT_V2 = b'ZKSSNP2\n'
MAGIC_SNAPSHOT_V1 = b'ZKSSNP1\n'
_SNAP_HDR = struct.Struct('>QQQI')    # index, zxid, epoch, crc32(payload)
_SNAP_HDR_V1 = struct.Struct('>QQI')  # index, zxid, crc32(payload)


def entry_zxid(entry: tuple) -> int:
    """The zxid a commit-log entry was sequenced at (store.py shapes:
    create[5], delete[2], set_data[3]; epoch and session control
    records carry the zxid current at the edge — they consume no zxid
    themselves; a multi is positioned at its LAST sub-entry's zxid)."""
    op = entry[0]
    if op == 'create':
        return entry[5]
    if op == 'delete':
        return entry[2]
    if op == 'set_data':
        return entry[3]
    if op in ('epoch', 'session_close'):
        return entry[2]
    if op == 'session':
        return entry[4]
    if op == 'reconfig':
        return entry[6]
    if op == 'multi':
        return entry_zxid(entry[1][-1])
    raise ValueError('unknown log entry %r' % (op,))


def _spec_encode_entry(entry: tuple) -> bytes:
    """The validating spec tier: jute primitives, field by field —
    exactly what the fast tier below must reproduce byte for byte."""
    w = JuteWriter()
    op = entry[0]
    w.write_byte(_TAGS[op])
    if op == 'epoch':
        _, epoch, zxid = entry
        w.write_long(epoch)
        w.write_long(zxid)
        return w.to_bytes()
    if op == 'session':
        _, sid, passwd, timeout, zxid = entry
        w.write_long(sid)
        w.write_buffer(passwd)
        w.write_int(timeout)
        w.write_long(zxid)
        return w.to_bytes()
    if op == 'session_close':
        _, sid, zxid, reason = entry
        w.write_long(sid)
        w.write_long(zxid)
        w.write_byte(_CLOSE_REASONS[reason])
        return w.to_bytes()
    if op == 'reconfig':
        _, version, phase, old_voters, new_voters, observers, \
            zxid = entry
        w.write_long(version)
        w.write_byte(_RECONFIG_PHASES[phase])
        for members in (old_voters, new_voters, observers):
            w.write_int(len(members))
            for m in members:
                w.write_int(m)
        w.write_long(zxid)
        return w.to_bytes()
    if op == 'multi':
        subs = entry[1]
        w.write_int(len(subs))
        for sub in subs:
            w.write_buffer(_spec_encode_entry(sub))
        return w.to_bytes()
    if op == 'create':
        _, path, data, acl, eph_owner, zxid, now = entry
        w.write_ustring(path)
        w.write_buffer(data)
        w.write_int(len(acl))
        for a in acl:
            w.write_int(int(a.perms))
            w.write_ustring(a.id.scheme)
            w.write_ustring(a.id.id)
        w.write_long(eph_owner)
        w.write_long(zxid)
        w.write_long(now)
    elif op == 'delete':
        _, path, zxid = entry
        w.write_ustring(path)
        w.write_long(zxid)
    else:
        assert op == 'set_data', op
        _, path, data, zxid, now = entry
        w.write_ustring(path)
        w.write_buffer(data)
        w.write_long(zxid)
        w.write_long(now)
    return w.to_bytes()


def _buf(data: bytes) -> bytes:
    """Jute buffer: length prefix (-1 for empty — the wire quirk the
    spec tier inherits from protocol/jute.py)."""
    if not data:
        return b'\xff\xff\xff\xff'
    return _I.pack(len(data)) + data


def encode_entry(entry: tuple) -> bytes:
    """Single-pass fast tier (the fastencode idiom: batched
    ``struct.pack`` + join); byte-identical to the spec tier by test."""
    op = entry[0]
    if op == 'set_data':
        _, path, data, zxid, now = entry
        p = path.encode('utf-8')
        return b''.join((b'\x03', _I.pack(len(p)), p, _buf(data),
                         _Q2.pack(zxid, now)))
    if op == 'epoch':
        return b'\x04' + _Q2.pack(entry[1], entry[2])
    if op == 'session':
        _, sid, passwd, timeout, zxid = entry
        return b''.join((b'\x05', struct.pack('>q', sid),
                         _buf(passwd), _I.pack(timeout),
                         struct.pack('>q', zxid)))
    if op == 'session_close':
        _, sid, zxid, reason = entry
        return (b'\x06' + _Q2.pack(sid, zxid)
                + bytes((_CLOSE_REASONS[reason],)))
    if op == 'reconfig':
        _, version, phase, old_voters, new_voters, observers, \
            zxid = entry
        parts = [b'\x08', struct.pack('>q', version),
                 bytes((_RECONFIG_PHASES[phase],))]
        for members in (old_voters, new_voters, observers):
            parts.append(_I.pack(len(members)))
            parts.extend(_I.pack(m) for m in members)
        parts.append(struct.pack('>q', zxid))
        return b''.join(parts)
    if op == 'multi':
        subs = entry[1]
        parts = [b'\x07', _I.pack(len(subs))]
        for sub in subs:
            body = encode_entry(sub)
            parts.append(_I.pack(len(body)))
            parts.append(body)
        return b''.join(parts)
    if op == 'create':
        _, path, data, acl, eph_owner, zxid, now = entry
        p = path.encode('utf-8')
        parts = [b'\x01', _I.pack(len(p)), p, _buf(data),
                 _I.pack(len(acl))]
        for a in acl:
            s = a.id.scheme.encode('utf-8')
            i = a.id.id.encode('utf-8')
            parts.append(_I.pack(int(a.perms)))
            parts.append(_buf(s))
            parts.append(_buf(i))
        parts.append(_Q3.pack(eph_owner, zxid, now))
        return b''.join(parts)
    if op == 'delete':
        _, path, zxid = entry
        p = path.encode('utf-8')
        return b''.join((b'\x02', _I.pack(len(p)), p,
                         struct.pack('>q', zxid)))
    raise ValueError('unknown log entry %r' % (op,))


def decode_entry(body: bytes) -> tuple:
    """Decode one record body back to the store.py entry tuple."""
    r = JuteReader(body)
    tag = r.read_byte()
    op = _OPS.get(tag)
    if op is None:
        raise ValueError('unknown WAL record tag %d' % (tag,))
    if op == 'create':
        path = r.read_ustring()
        data = bytes(r.read_buffer())
        n = r.read_int()
        # bounded by what can physically fit (an empty ACL encodes to
        # 12 bytes) — never by an arbitrary cap tighter than what the
        # write path accepts, or a legitimately-acked record would
        # poison its own recovery
        if not 0 <= n <= len(body) // 12:
            raise ValueError('insane ACL count %d' % (n,))
        acl = tuple(
            ACL(_perm(r.read_int()),
                Id(r.read_ustring(), r.read_ustring()))
            for _ in range(n))
        eph_owner = r.read_long()
        zxid = r.read_long()
        now = r.read_long()
        return ('create', path, data, acl, eph_owner, zxid, now)
    if op == 'delete':
        return ('delete', r.read_ustring(), r.read_long())
    if op == 'epoch':
        return ('epoch', r.read_long(), r.read_long())
    if op == 'session':
        return ('session', r.read_long(), bytes(r.read_buffer()),
                r.read_int(), r.read_long())
    if op == 'session_close':
        sid, zxid = r.read_long(), r.read_long()
        reason = _CLOSE_NAMES.get(r.read_byte())
        if reason is None:
            raise ValueError('unknown session-close reason')
        return ('session_close', sid, zxid, reason)
    if op == 'reconfig':
        version = r.read_long()
        phase = _RECONFIG_NAMES.get(r.read_byte())
        if phase is None:
            raise ValueError('unknown reconfig phase')
        sets = []
        for _ in range(3):
            n = r.read_int()
            # bounded by what can physically fit (4 bytes per member)
            if not 0 <= n <= len(body) // 4:
                raise ValueError('insane member count %d' % (n,))
            sets.append(tuple(r.read_int() for _ in range(n)))
        return ('reconfig', version, phase, sets[0], sets[1],
                sets[2], r.read_long())
    if op == 'multi':
        n = r.read_int()
        # bounded by what can physically fit (a sub-record is at least
        # its 4-byte length prefix + 1-byte tag)
        if not 0 < n <= len(body) // 5:
            raise ValueError('insane multi sub-count %d' % (n,))
        subs = []
        for _ in range(n):
            sub = decode_entry(bytes(r.read_buffer()))
            if sub[0] not in ('create', 'delete', 'set_data'):
                raise ValueError('control record inside a multi')
            subs.append(sub)
        return ('multi', tuple(subs))
    return ('set_data', r.read_ustring(), bytes(r.read_buffer()),
            r.read_long(), r.read_long())


def _perm(v: int):
    from ..protocol.consts import Perm
    return Perm(v)


# ---------------------------------------------------------------------
# Directory scan: segments + snapshots (shared by recovery and the
# ``wal`` CLI subcommand, so the two can never disagree on validity).
# ---------------------------------------------------------------------


@dataclasses.dataclass
class SegmentInfo:
    path: str
    start_index: int
    #: decoded (index, entry) pairs up to the first invalid record
    records: list
    #: byte offset of the first invalid record (== file size when the
    #: whole segment is valid) — the truncation point a reopening WAL
    #: cuts the file back to
    valid_bytes: int
    size: int
    #: 'ok' | 'torn' (truncated tail: short header/body) |
    #: 'crc' (checksum mismatch) | 'corrupt' (bad magic/length/decode)
    status: str
    error: str | None = None

    @property
    def end_index(self) -> int:
        return self.start_index + len(self.records)


@dataclasses.dataclass
class SnapshotInfo:
    path: str
    index: int
    zxid: int
    valid: bool
    nodes: dict | None = None
    error: str | None = None
    #: leadership epoch at capture (format 2 stamp)
    epoch: int = 0
    #: live sessions at capture, {sid: (passwd, timeout)} (format 3
    #: payload; empty for older images)
    sessions: dict = dataclasses.field(default_factory=dict)
    #: membership config at capture (format 3 payload 'config' key;
    #: None for older images or never-reconfigured ensembles)
    config: dict | None = None


@dataclasses.dataclass
class WalScan:
    dir: str
    segments: list          # SegmentInfo, by start_index
    snapshots: list         # SnapshotInfo, by index (valid and not)

    def newest_valid_snapshot(self) -> SnapshotInfo | None:
        for s in reversed(self.snapshots):
            if s.valid:
                return s
        return None


def _scan_segment(path: str, start_index: int,
                  with_entries: bool = True) -> SegmentInfo:
    with open(path, 'rb') as f:
        buf = f.read()
    size = len(buf)
    if not buf.startswith(MAGIC_SEGMENT):
        return SegmentInfo(path, start_index, [], 0, size, 'corrupt',
                           'bad segment magic')
    off = len(MAGIC_SEGMENT)
    records: list = []
    status, error = 'ok', None
    idx = start_index
    while off < size:
        if off + _REC_HDR.size > size:
            status, error = 'torn', 'truncated record header'
            break
        ln, crc = _REC_HDR.unpack_from(buf, off)
        if not 0 < ln <= MAX_RECORD:
            status, error = 'corrupt', 'insane record length %d' % ln
            break
        if off + _REC_HDR.size + ln > size:
            status, error = 'torn', 'truncated record body'
            break
        body = buf[off + _REC_HDR.size:off + _REC_HDR.size + ln]
        if crc32c(body) != crc:
            status, error = 'crc', ('record %d fails CRC32C' % (idx,))
            break
        try:
            entry = decode_entry(body) if with_entries else None
        except Exception as e:
            status, error = 'corrupt', ('record %d undecodable: %s'
                                        % (idx, e))
            break
        records.append((idx, entry))
        off += _REC_HDR.size + ln
        idx += 1
    return SegmentInfo(path, start_index, records, off, size, status,
                       error)


def _read_snapshot(path: str, load_nodes: bool = True) -> SnapshotInfo:
    name = os.path.basename(path)
    try:
        with open(path, 'rb') as f:
            buf = f.read()
        dict_payload = False
        if buf.startswith(MAGIC_SNAPSHOT):
            index, zxid, epoch, crc = _SNAP_HDR.unpack_from(
                buf, len(MAGIC_SNAPSHOT))
            body_off = len(MAGIC_SNAPSHOT) + _SNAP_HDR.size
            dict_payload = True       # {'nodes', 'sessions'}
        elif buf.startswith(MAGIC_SNAPSHOT_V2):
            index, zxid, epoch, crc = _SNAP_HDR.unpack_from(
                buf, len(MAGIC_SNAPSHOT_V2))
            body_off = len(MAGIC_SNAPSHOT_V2) + _SNAP_HDR.size
        elif buf.startswith(MAGIC_SNAPSHOT_V1):
            # pre-election format: no epoch in the stamp
            index, zxid, crc = _SNAP_HDR_V1.unpack_from(
                buf, len(MAGIC_SNAPSHOT_V1))
            epoch = 0
            body_off = len(MAGIC_SNAPSHOT_V1) + _SNAP_HDR_V1.size
        else:
            raise ValueError('bad snapshot magic')
        payload = buf[body_off:]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise ValueError('snapshot payload fails CRC')
        nodes, sessions, config = None, {}, None
        if load_nodes:
            image = pickle.loads(payload)
            if dict_payload:
                nodes = image['nodes']
                sessions = image.get('sessions', {})
                config = image.get('config')
            else:
                nodes = image
            if '/' not in nodes:
                raise ValueError('snapshot image has no root')
        return SnapshotInfo(path, index, zxid, True, nodes,
                            epoch=epoch, sessions=sessions,
                            config=config)
    except Exception as e:
        # parse the stamp out of the filename so the CLI can still
        # list the corrupt file next to its intended position
        idx = -1
        parts = name.split('.')
        if len(parts) >= 2 and parts[1].isdigit():
            idx = int(parts[1])
        return SnapshotInfo(path, idx, -1, False, None, str(e))


def scan_dir(path: str, with_entries: bool = True,
             load_snapshots: bool = True) -> WalScan:
    """Inventory a WAL directory.  Never mutates it — reopening for
    writes (``WriteAheadLog``) is what truncates a torn tail.

    Co-tenancy contract: only the ``wal.``/``snap.`` prefixes belong
    to this module.  The black-box flight recorder
    (utils/blackbox.py) keeps its ``blackbox.<member>.log`` rings in
    the same directory, invisible to this scan and to
    :func:`reset_dir` — a member's telemetry must survive its own
    snapshot bootstrap."""
    segments, snapshots = [], []
    try:
        names = sorted(os.listdir(path))
    except FileNotFoundError:
        names = []
    for name in names:
        full = os.path.join(path, name)
        if name.endswith('.tmp'):
            continue                  # in-flight snapshot: not durable
        if name.startswith('wal.') and name.endswith('.log'):
            try:
                start = int(name.split('.')[1])
            except (IndexError, ValueError):
                continue
            segments.append(_scan_segment(full, start,
                                          with_entries=with_entries))
        elif name.startswith('snap.'):
            snapshots.append(_read_snapshot(full,
                                            load_nodes=load_snapshots))
    segments.sort(key=lambda s: s.start_index)
    snapshots.sort(key=lambda s: s.index)
    return WalScan(path, segments, snapshots)


@dataclasses.dataclass
class Recovery:
    """What recovery reconstructed from disk."""

    nodes: dict             # full node map (root included)
    zxid: int
    last_index: int         # next append slot (one past newest entry)
    snapshot_index: int     # -1 when no snapshot was used
    snapshot_zxid: int
    replayed: int           # log entries applied on top of the image
    torn: bool              # a torn/invalid tail was tolerated
    detail: str = ''
    #: newest leadership epoch on disk (snapshot stamp or epoch
    #: control records, whichever is higher) — what a recovered
    #: member votes with (server/election.py)
    epoch: int = 0
    #: sessions alive at the crash, {sid: (passwd, timeout)} — the
    #: snapshot's table plus the session control records replayed by
    #: log index; :func:`restore_sessions` re-arms them with a fresh
    #: expiry clock so ephemerals survive a restart inside the
    #: session timeout
    sessions: dict = dataclasses.field(default_factory=dict)
    #: newest membership config on disk (snapshot 'config' key plus
    #: reconfig control records replayed by log index) — a dict
    #: ``{'version', 'phase', 'voters', 'old_voters', 'observers'}``,
    #: or None when this ensemble was never reconfigured.  A
    #: recovered ``phase == 'joint'`` is an IN-PROGRESS reconfig: the
    #: member promoted over this WAL must finish it (commit the final
    #: record) before the joint window can close.
    config: dict | None = None


def recover_state(path: str, trace=None) -> Recovery:
    """Load the newest valid snapshot, replay the log tail, tolerate a
    torn final record.  Replay stops at the first invalid record and
    ignores later segments (bytes after a tear are unordered garbage).

    ``trace`` (a utils/trace.TraceRing) gets a ``WAL_RECOVER`` span so
    campaign dumps show recovery next to the ops around it."""
    from .store import NodeTree, Znode

    t0 = time.monotonic()
    scan = scan_dir(path)
    snap = scan.newest_valid_snapshot()
    tree = NodeTree()
    if snap is not None:
        tree.install({'zxid': snap.zxid, 'nodes': snap.nodes})
    base_zxid = tree.zxid
    base_index = snap.index if snap is not None else 0
    epoch = snap.epoch if snap is not None else 0
    sessions = dict(snap.sessions) if snap is not None else {}
    config = (dict(snap.config)
              if snap is not None and snap.config else None)
    replayed = 0
    torn = False
    last_index = base_index
    for n, seg in enumerate(scan.segments):
        if seg.end_index <= base_index and seg.status == 'ok':
            last_index = max(last_index, seg.end_index)
            continue                   # fully under the snapshot
        nxt = (scan.segments[n + 1].start_index
               if n + 1 < len(scan.segments) else None)
        if nxt is not None and nxt <= base_index:
            # even a corrupt segment is irrelevant when its whole
            # intended range [start, next segment's start) is inside
            # the snapshot image — do not let it stop the replay of
            # newer, valid segments
            last_index = max(last_index, nxt)
            continue
        for idx, entry in seg.records:
            if entry[0] == 'epoch':
                # control record: adopt the epoch (zxid filter does
                # not apply — a bump consumes no zxid), never applied
                # to the tree
                epoch = max(epoch, entry[1])
                last_index = max(last_index, idx + 1)
                continue
            if entry[0] == 'reconfig':
                # membership control record: filtered by LOG INDEX
                # like the session records (the snapshot's 'config'
                # key covers everything before its stamp)
                if idx >= base_index:
                    _, ver, phase, old_v, new_v, obs, _z = entry
                    config = {'version': ver, 'phase': phase,
                              'voters': tuple(new_v),
                              'old_voters': (tuple(old_v)
                                             if phase == 'joint'
                                             else None),
                              'observers': tuple(obs)}
                last_index = max(last_index, idx + 1)
                continue
            if entry[0] in ('session', 'session_close'):
                # session control records carry the zxid current at
                # the edge, so the zxid filter cannot place them:
                # filter by LOG INDEX against the snapshot stamp (the
                # image's session table covers everything before it)
                if idx >= base_index:
                    if entry[0] == 'session':
                        sessions[entry[1]] = (entry[2], entry[3])
                    else:
                        sessions.pop(entry[1], None)
                last_index = max(last_index, idx + 1)
                continue
            if entry_zxid(entry) <= base_zxid:
                last_index = max(last_index, idx + 1)
                continue               # covered by the image
            tree.apply_entry(entry)
            _restore_seq(tree, entry)
            replayed += 1
            last_index = max(last_index, idx + 1)
        if seg.status != 'ok':
            torn = True
            break                      # nothing after a tear is usable
    if snap is None and not scan.segments:
        tree.nodes.setdefault('/', Znode())
    detail = ('snapshot idx=%d zxid=%d + %d replayed%s'
              % (base_index, base_zxid, replayed,
                 ' (torn tail tolerated)' if torn else '')
              if snap is not None else
              '%d replayed from empty tree%s'
              % (replayed, ' (torn tail tolerated)' if torn else ''))
    rec = Recovery(nodes=tree.nodes, zxid=tree.zxid,
                   last_index=last_index,
                   snapshot_index=snap.index if snap else -1,
                   snapshot_zxid=snap.zxid if snap else 0,
                   replayed=replayed, torn=torn, detail=detail,
                   epoch=epoch, sessions=sessions, config=config)
    if trace is not None:
        trace.note('WAL_RECOVER', path=path, zxid=rec.zxid,
                   kind='recovery',
                   duration_ms=round((time.monotonic() - t0) * 1e3, 3))
    log.info('recovered %s: %s -> zxid %d', path, detail, rec.zxid)
    return rec


def _advance_seq(tree, path: str) -> None:
    """Advance the parent's sequential counter past ``path``'s
    10-digit suffix (when it has one).  The ONE copy of the
    heuristic — replay recovery and leader promotion both use it; it
    can only over-advance a counter (a user node that merely looks
    sequential skips numbers — harmless), never reuse one."""
    name = path.rsplit('/', 1)[-1]
    if len(name) > 10 and name[-10:].isdigit():
        from .store import parent_path
        parent = tree.nodes.get(parent_path(path))
        if parent is not None:
            parent.seq = max(parent.seq, int(name[-10:]) + 1)


def _restore_seq(tree, entry) -> None:
    """Leader-side sequential counters are resolved *before* a create
    is logged, so replay must re-derive them: a recovered leader whose
    parent.seq lagged would hand out an already-used number."""
    if entry[0] == 'create':
        _advance_seq(tree, entry[1])
    elif entry[0] == 'multi':
        for sub in entry[1]:
            _restore_seq(tree, sub)


# ---------------------------------------------------------------------
# The log itself.
# ---------------------------------------------------------------------

METRIC_FSYNC = 'zookeeper_fsync_latency_ms'
METRIC_APPEND_BYTES = 'zkstream_wal_append_bytes'

FSYNC_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                 50.0, 100.0, 250.0)
APPEND_BUCKETS = (32, 64, 128, 256, 512, 1024, 4096, 16384, 65536)

DEFAULT_SEGMENT_BYTES = 4 * 1024 * 1024
DEFAULT_SEGMENT_AGE_S = 300.0
SYNC_POLICIES = ('always', 'tick', 'never')
#: Fast-device short-circuit: when the EWMA of measured device sync
#: latency sits under this, the tick group fsync runs inline instead
#: of on the executor — on tmpfs-class devices (~10 us) the thread
#: handoff + completion callback cost more than the fsync itself,
#: while on a real disk (100s of us and up) overlapping the loop wins.
FAST_SYNC_MS = 0.15
#: Snapshot fallback depth: how many older snapshots survive a new one.
KEEP_SNAPSHOTS = 2


def wal_enabled() -> bool:
    """Global kill switch (mirrors the cork's ``ZKSTREAM_NO_CORK``)."""
    return os.environ.get('ZKSTREAM_NO_WAL') != '1'


def default_wal_dir() -> str | None:
    """The ambient WAL directory, if any (``ZKSTREAM_WAL_DIR``)."""
    return os.environ.get('ZKSTREAM_WAL_DIR') or None


class WriteAheadLog:
    """One directory of CRC32C-framed segments plus snapshots.

    Opening an existing directory continues it: the scan finds the
    newest index, a torn tail (the signature of a crash mid-write) is
    truncated back to the last whole record, and appends resume from
    there.  ``bind(tree)`` attaches the tree snapshots are taken of.
    """

    def __init__(self, path: str, *, sync: str = 'tick',
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 segment_age_s: float = DEFAULT_SEGMENT_AGE_S,
                 collector=None, faults=None):
        assert sync in SYNC_POLICIES, sync
        self.dir = path
        self.sync = sync
        self.segment_bytes = segment_bytes
        self.segment_age_s = segment_age_s
        #: Optional seeded FaultInjector (io/faults.py 'disk'
        #: category): fsync latency / fsync error injection.
        self.faults = faults
        #: Optional gate a snapshot must pass (the follower mirror
        #: sets "replica caught up to the mirror" here, so a fuzzy
        #: image can never stamp entries the tree hasn't applied).
        self.snapshot_gate = None
        #: Optional utils/trace.TraceRing (the owning member's —
        #: server/server.py wires it): every append records a
        #: ``WAL_APPEND`` span and every completed fsync a
        #: ``GROUP_FSYNC`` span stamped with the barrier's batch size,
        #: so a txn's durability leg is traceable by zxid.
        self.trace = None
        #: Optional utils/metrics.TickLedger: loop-blocking sync time
        #: (sync='always' appends, the tick-sync fast path) lands in
        #: the ``fsync_gate`` tick phase.
        self.ledger = None
        self._tree = None
        # counters (gauges read these; cheap ints, no hot-path cost)
        self.appends = 0
        self.fsyncs = 0
        self.sync_errors = 0
        self.snapshots_taken = 0
        self.last_zxid = 0
        self.durable_zxid = 0
        self.next_index = 0
        self._written = 0             # bytes written to current segment
        self._durable = 0             # bytes covered by the last fsync
        #: bytes the newest *completed* fsync attempt covered, even a
        #: failed one — the ack gate releases on attempt, so a broken
        #: device degrades to acked-but-not-durable (counted in
        #: ``sync_errors``, demoted by the recovery invariant's
        #: floor) instead of wedging every reply forever
        self._attempted = 0
        #: cumulative appends covered by completed fsyncs — the delta
        #: at each fsync is that barrier's batch size (GROUP_FSYNC
        #: span + the group-commit story in the timeline)
        self._synced_appends = 0
        self._sync_scheduled = False
        self._inflight = False        # a group fsync is on the executor
        self._waiters: list = []      # send-plane releases awaiting it
        #: EWMA of measured device sync latency, ms (None until the
        #: first sync) — drives the FAST_SYNC_MS short-circuit
        self._sync_ewma_ms: float | None = None
        self._closed = False
        self._closed_segments: list[tuple[int, str]] = []
        self._snapshot_files: list[tuple[int, str]] = []
        self._fsync_hist = None
        self._append_hist = None
        if collector is not None:
            self.bind_metrics(collector)

        self._open_dir()

    def _open_dir(self) -> None:
        """Scan-and-continue the directory: shared by construction and
        :meth:`reopen`.  Mirrors :func:`recover_state`'s stop-at-
        first-invalid rule exactly — anything replay would never reach
        is quarantined (renamed ``*.dead``), never silently rejoined
        to the live history."""
        os.makedirs(self.dir, exist_ok=True)
        scan = scan_dir(self.dir, with_entries=True)
        self._closed_segments = []
        self._snapshot_files = []
        self.next_index = 0
        last_zxid = 0
        for s in scan.snapshots:
            if s.valid:
                self._snapshot_files.append((s.index, s.path))
                last_zxid = max(last_zxid, s.zxid)
        snap = scan.newest_valid_snapshot()
        base_index = snap.index if snap is not None else 0
        kept: list = []
        dead = False
        for n, seg in enumerate(scan.segments):
            if dead:
                # recovery stopped before this segment: its entries
                # are history the served state never contained —
                # rejoining them to the live log would let the NEXT
                # recovery replay across the gap
                self._quarantine(seg.path)
                continue
            if seg.status != 'ok':
                nxt = (scan.segments[n + 1].start_index
                       if n + 1 < len(scan.segments) else None)
                if nxt is not None and nxt <= base_index:
                    # wholly superseded by the snapshot image (the
                    # same rule recover_state applies): irrelevant to
                    # replay — quarantine just this one and go on
                    self._quarantine(seg.path)
                    continue
                # truncate the torn/invalid tail in place: bytes after
                # the last whole record are garbage, and leaving them
                # would poison the next recovery's stop-at-first-
                # invalid rule once a fresh segment follows them
                log.warning('truncating %s at %d (%s: %s)',
                            seg.path, seg.valid_bytes, seg.status,
                            seg.error)
                with open(seg.path, 'r+b') as f:
                    f.truncate(seg.valid_bytes)
                seg = dataclasses.replace(seg, size=seg.valid_bytes,
                                          status='ok', error=None)
                dead = True           # later segments are unreachable
            self.next_index = max(self.next_index, seg.end_index)
            if seg.records:
                last_zxid = max(last_zxid,
                                entry_zxid(seg.records[-1][1]))
            kept.append(seg)
        self.last_zxid = self.durable_zxid = last_zxid
        tail = kept[-1] if kept else None
        for seg in kept[:-1]:
            self._closed_segments.append((seg.start_index, seg.path))
        if tail is not None:
            # continue the newest kept segment in place (the bytes
            # already there survived a restart: they are on disk)
            self._file = open(tail.path, 'ab')
            self._seg_path = tail.path
            self._seg_start = tail.start_index
            self._written = self._durable = tail.size
            self._attempted = tail.size
            self._seg_gen = getattr(self, '_seg_gen', 0) + 1
            self._seg_opened = time.monotonic()
        else:
            self._open_segment()

    @staticmethod
    def _quarantine(path: str) -> None:
        dead = path + '.dead'
        log.warning('quarantining unreachable WAL segment %s', path)
        try:
            os.replace(path, dead)
        except OSError:  # pragma: no cover - permissions
            pass

    # -- metrics --

    def bind_metrics(self, collector) -> None:
        self._fsync_hist = collector.histogram(
            METRIC_FSYNC, 'WAL fsync latency, ms',
            buckets=FSYNC_BUCKETS)
        self._append_hist = collector.histogram(
            METRIC_APPEND_BYTES, 'Bytes per WAL record appended',
            buckets=APPEND_BUCKETS)
        # gauges are never idempotent on a Collector; two WALs sharing
        # one collector keep the first registrant's series
        for name, fn, help_text in (
                ('zkstream_wal_segments',
                 lambda: len(self._closed_segments) + 1,
                 'Live WAL segment files'),
                ('zkstream_wal_bytes', lambda: self.total_bytes(),
                 'Bytes across live WAL segments'),
                ('zkstream_wal_snapshots',
                 lambda: len(self._snapshot_files),
                 'Durable snapshot files'),
                ('zkstream_wal_last_index', lambda: self.next_index,
                 'One past the newest appended log index'),
                ('zkstream_wal_unsynced_bytes',
                 lambda: self._written - self._durable,
                 'Bytes appended to the open segment but not fsynced')):
            try:
                collector.gauge(name, fn, help_text)
            except ValueError:
                pass

    def total_bytes(self) -> int:
        n = self._written
        for _start, p in self._closed_segments:
            try:
                n += os.path.getsize(p)
            except OSError:
                pass
        return n

    # -- wiring --

    def bind(self, tree) -> None:
        """Attach the tree snapshots serialize (ZKDatabase for the
        leader, the replica store for a follower mirror)."""
        self._tree = tree

    # -- append path --

    def append(self, entry: tuple) -> int:
        """Append one committed txn; returns its absolute log index.
        Runs *before* the txn's ack is corked (store.py `_commit`), so
        the sync policy's barrier covers it."""
        assert not self._closed, 'append to a closed WAL'
        body = encode_entry(entry)
        rec = _REC_HDR.pack(len(body), crc32c(body)) + body
        self._file.write(rec)
        self._written += len(rec)
        self.appends += 1
        idx = self.next_index
        self.next_index += 1
        self.last_zxid = entry_zxid(entry)
        if self._append_hist is not None:
            self._append_hist.observe(len(rec))
        if self.trace is not None:
            self.trace.note('WAL_APPEND', zxid=self.last_zxid,
                            kind='server', nbytes=len(rec))
        if self.sync == 'always':
            if self.ledger is not None:
                self.ledger.enter('fsync_gate')
                try:
                    self.sync_now()
                finally:
                    self.ledger.exit()
            else:
                self.sync_now()
        elif self.sync == 'tick':
            self._schedule_tick_sync()
        else:
            self._file.flush()        # OS-buffered only
        self._maybe_roll()
        return idx

    def _schedule_tick_sync(self) -> None:
        if self._sync_scheduled:
            return
        self._sync_scheduled = True
        try:
            ambient_loop().call_soon(self._tick_sync)
        except RuntimeError:
            self._sync_scheduled = False
            self.sync_now()           # no loop: degrade to always

    def _tick_sync(self) -> None:
        self._sync_scheduled = False
        if self._closed:
            return
        if self.ledger is not None:
            # the fast-device short-circuit fsyncs inline here: that
            # is the tick's loop-blocked durability time
            self.ledger.enter('fsync_gate')
            try:
                self._ensure_group_sync()
            finally:
                self.ledger.exit()
        else:
            self._ensure_group_sync()

    # -- the ack gate (group commit riding the send-plane cork) --

    def gate_flush(self, release) -> bool:
        """The send-plane's durability gate (io/sendplane.py
        ``barrier``): True when every appended txn is already covered
        by a completed fsync attempt — the corked acks may leave.
        Otherwise the flush stays corked, ONE group fsync runs on an
        executor thread (the event loop keeps serving — real ZK's
        sync-thread shape), and ``release`` re-flushes when it
        completes.  ``sync='never'`` forfeits the gate;
        ``sync='always'`` already fsynced inside ``append`` and only
        re-syncs here after an earlier failure."""
        if self._closed or self.sync == 'never':
            return True
        if self._durable >= self._written \
                or self._attempted >= self._written:
            return True
        if self.sync == 'always':
            self.sync_now()
            return True
        self._ensure_group_sync()     # may complete inline (fast dev)
        if self._durable >= self._written \
                or self._attempted >= self._written:
            return True
        self._waiters.append(release)
        return False

    def _ensure_group_sync(self) -> None:
        """Start (at most one) group fsync covering everything written
        so far — inline when the device has been measuring fast (the
        executor round trip would cost more than the fsync), off-loop
        otherwise."""
        if self._inflight or self._closed:
            return
        if self._durable >= self._written:
            self._drain_waiters()
            return
        fast = (self._sync_ewma_ms is not None
                and self._sync_ewma_ms < FAST_SYNC_MS)
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None               # no loop to overlap with
        if fast or loop is None:
            self.sync_now()
            self._drain_waiters()
            return
        delay_ms, err = (self.faults.fsync_fault()
                         if self.faults is not None else (0.0, False))
        self._file.flush()
        snap_off, snap_zxid = self._written, self.last_zxid
        snap_n = self.appends
        fd = self._file.fileno()

        def work() -> float:
            t0 = time.perf_counter()
            if delay_ms > 0:
                time.sleep(delay_ms / 1000.0)   # device latency: it
                # delays acks, not the loop — exactly like real fsync
            if err:
                raise OSError('injected fsync error')
            os.fsync(fd)
            return (time.perf_counter() - t0) * 1000.0

        self._inflight = True
        gen = self._seg_gen
        fut = loop.run_in_executor(None, work)
        fut.add_done_callback(
            lambda f: self._group_sync_done(f, snap_off, snap_zxid,
                                            gen, snap_n))

    def _group_sync_done(self, fut, snap_off: int, snap_zxid: int,
                         gen: int, snap_n: int = 0) -> None:
        self._inflight = False
        if gen != self._seg_gen:
            # the segment rolled while this fsync ran: roll's
            # synchronous sync already covered those bytes, and the
            # old-segment offsets must not touch the new segment's
            # accounting (a spurious EBADF from the closed fd is the
            # same stale completion).  Re-gate any waiters against
            # the current segment.
            fut.exception()           # consume, never raises here
            self._drain_waiters()
            if self._written > max(self._durable, self._attempted):
                self._ensure_group_sync()
            return
        self._attempted = max(self._attempted, snap_off)
        if self._closed:
            self._drain_waiters()
            return
        exc = fut.exception()
        if exc is None:
            dur_ms = fut.result()
            self._note_sync(dur_ms, snap_n=snap_n,
                            snap_zxid=snap_zxid)
            if snap_off > self._durable:
                self._durable = snap_off
                self.durable_zxid = snap_zxid
        else:
            self.sync_errors += 1
            log.warning('WAL group fsync failed (%s); acked writes '
                        'since zxid %d are not durable', exc,
                        self.durable_zxid)
        self._drain_waiters()
        if self._written > max(self._durable, self._attempted):
            # appends landed while the fsync ran: cover them too
            self._ensure_group_sync()

    def _note_sync(self, dur_ms: float, snap_n: int = 0,
                   snap_zxid: int = 0) -> None:
        self.fsyncs += 1
        if self._fsync_hist is not None:
            self._fsync_hist.observe(dur_ms)
        if self.trace is not None and snap_n > self._synced_appends:
            # ONE span for the whole barrier, shared by every txn it
            # covered: stamped with the newest covered zxid and the
            # batch size (the group-commit shape, visible per write
            # in the merged timeline)
            self.trace.note('GROUP_FSYNC', zxid=snap_zxid,
                            kind='server',
                            batch=snap_n - self._synced_appends,
                            duration_ms=round(dur_ms, 3))
        if snap_n > self._synced_appends:
            self._synced_appends = snap_n
        self._sync_ewma_ms = (dur_ms if self._sync_ewma_ms is None
                              else 0.8 * self._sync_ewma_ms
                              + 0.2 * dur_ms)

    def _drain_waiters(self) -> None:
        waiters, self._waiters = self._waiters, []
        for release in waiters:
            try:
                release()
            except Exception:  # pragma: no cover - plane teardown
                log.exception('WAL gate release failed')

    def sync_for_flush(self) -> None:
        """The *synchronous* barrier: whatever the caller is about to
        put on the wire must be durable when this returns.  Used by
        the send-plane's ``flush_hard`` (fault-injected delivery,
        close paths) and the replication control channel's forwarded-
        write acks.  No-op under ``sync='never'`` — that policy
        explicitly forfeits the guarantee — and when nothing is
        pending."""
        if self.sync == 'never' or self._closed:
            return
        if self._durable != self._written:
            self.sync_now()

    def sync_now(self) -> bool:
        """Flush + fsync the open segment, blocking; returns False on
        an fsync error (injected or real — the write is then *not*
        durable and ``sync_errors``/``durable_zxid`` say so; retried
        at the next barrier)."""
        if self._durable >= self._written:
            return True
        t0 = time.perf_counter()
        snap_off, snap_zxid = self._written, self.last_zxid
        snap_n = self.appends
        try:
            if self.faults is not None:
                delay_ms, err = self.faults.fsync_fault()
                if delay_ms > 0:
                    time.sleep(delay_ms / 1000.0)
                if err:
                    raise OSError('injected fsync error')
            self._file.flush()
            os.fsync(self._file.fileno())
        except OSError as e:
            self.sync_errors += 1
            self._attempted = max(self._attempted, snap_off)
            log.warning('WAL fsync failed (%s); acked writes since '
                        'zxid %d are not yet durable', e,
                        self.durable_zxid)
            return False
        self._note_sync((time.perf_counter() - t0) * 1000.0,
                        snap_n=snap_n, snap_zxid=snap_zxid)
        self._attempted = max(self._attempted, snap_off)
        if snap_off > self._durable:
            self._durable = snap_off
            self.durable_zxid = snap_zxid
        return True

    # -- rotation + snapshots --

    def _seg_name(self, start: int) -> str:
        return os.path.join(self.dir, 'wal.%016d.log' % (start,))

    def _open_segment(self) -> None:
        self._seg_start = self.next_index
        self._seg_path = self._seg_name(self._seg_start)
        self._file = open(self._seg_path, 'ab')
        if self._file.tell() == 0:
            self._file.write(MAGIC_SEGMENT)
            self._file.flush()
        # offsets are per-segment: everything (durable, attempted, the
        # in-flight-fsync generation) re-bases here, or a stale count
        # from the previous segment would read as coverage of bytes
        # this segment has not fsynced
        self._written = self._durable = self._file.tell()
        self._attempted = self._written
        self._seg_gen = getattr(self, '_seg_gen', 0) + 1
        self._seg_opened = time.monotonic()

    def _maybe_roll(self) -> None:
        if (self._written < self.segment_bytes
                and (time.monotonic() - self._seg_opened)
                < self.segment_age_s):
            return
        if self.snapshot_gate is not None and not self.snapshot_gate():
            return                    # fuzzy image not consistent yet
        self.roll()

    def roll(self) -> None:
        """Close the open segment (fsynced), open the next, and take
        the snapshot that anchors truncation of everything before it."""
        self.sync_now()
        self._file.close()
        self._closed_segments.append((self._seg_start, self._seg_path))
        self._open_segment()
        self.snapshot_now()

    def snapshot_now(self) -> bool:
        """Take one fuzzy snapshot: stamp + image captured atomically
        in this tick, persisted concurrently with later applies (the
        file write/fsync/rename runs on an executor thread when a loop
        is available), truncation scheduled only once the file is
        durable."""
        tree = self._tree
        if tree is None:
            return False
        index, zxid = self.next_index, tree.zxid
        epoch = getattr(tree, 'epoch', 0)
        # format 3: the session table enters the image (captured in
        # the same synchronous tick as the stamp), so a restart inside
        # the session timeout keeps sessions — and their ephemerals
        snap_sessions = getattr(tree, 'session_snapshot',
                                lambda: {})()
        image = {'nodes': tree.nodes, 'sessions': snap_sessions}
        snap_config = getattr(tree, 'config_snapshot',
                              lambda: None)()
        if snap_config is not None:
            image['config'] = snap_config
        payload = pickle.dumps(image,
                               protocol=pickle.HIGHEST_PROTOCOL)
        final = os.path.join(self.dir, 'snap.%016d' % (index,))
        tmp = final + '.tmp'
        blob = (MAGIC_SNAPSHOT
                + _SNAP_HDR.pack(index, zxid, epoch,
                                 zlib.crc32(payload) & 0xFFFFFFFF)
                + payload)

        def persist() -> None:
            with open(tmp, 'wb') as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            if self._closed:
                # the log closed while this image was in flight: do
                # not materialize state into a directory the owner
                # already considers final
                os.unlink(tmp)
                return
            os.replace(tmp, final)

        def done() -> None:
            if self._closed:
                return
            self.snapshots_taken += 1
            self._snapshot_files.append((index, final))
            self._truncate_to(index)

        try:
            loop = ambient_loop()
            fut = loop.run_in_executor(None, persist)

            def _cb(f):
                if f.exception() is None:
                    done()
                else:  # pragma: no cover - disk-full class failures
                    log.warning('snapshot %s failed: %s', final,
                                f.exception())
            fut.add_done_callback(_cb)
        except RuntimeError:
            persist()                 # no loop: synchronous
            done()
        return True

    def _truncate_to(self, index: int) -> None:
        """Snapshot-anchored truncation.  Old snapshots beyond the
        keep depth go first; then closed segments wholly below the
        *oldest kept* snapshot — not merely the newest (``index``) —
        are dropped, so a recovery forced onto an older snapshot by a
        corrupt newer one still finds every entry it must replay."""
        self._snapshot_files.sort()
        while len(self._snapshot_files) > KEEP_SNAPSHOTS:
            _idx, p = self._snapshot_files.pop(0)
            try:
                os.unlink(p)
            except OSError:
                pass
        anchor = min((i for i, _p in self._snapshot_files),
                     default=index)
        keep: list[tuple[int, str]] = []
        ends = ([s for s, _ in self._closed_segments[1:]]
                + [self._seg_start])
        for (start, p), end in zip(self._closed_segments, ends):
            if end <= anchor:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            else:
                keep.append((start, p))
        self._closed_segments = keep

    # -- crash simulation (chaos campaigns) --

    def materialize_crash(self, dst: str,
                          before_fsync: bool) -> int:
        """Write the directory a SIGKILL would leave behind into
        ``dst`` and return the zxid floor known durable in it.

        ``before_fsync=True`` is the harsher window: the open
        segment's un-fsynced tail is gone (the page cache died with
        the OS's cooperation withdrawn); ``False`` models dying just
        after the pending fsync completed.  Closed segments and
        completed snapshots were fsynced before becoming visible, so
        they survive either window whole; ``*.tmp`` never survives."""
        os.makedirs(dst, exist_ok=True)
        for _start, p in self._closed_segments:
            self._copy(p, dst)
        for _idx, p in self._snapshot_files:
            self._copy(p, dst)
        self._file.flush()
        n = self._durable if before_fsync else self._written
        with open(self._seg_path, 'rb') as f:
            data = f.read(n)
        with open(os.path.join(dst,
                               os.path.basename(self._seg_path)),
                  'wb') as f:
            f.write(data)
        return self.durable_zxid if before_fsync else self.last_zxid

    @staticmethod
    def _copy(src: str, dst_dir: str) -> None:
        try:
            with open(src, 'rb') as f:
                data = f.read()
        except OSError:
            return
        with open(os.path.join(dst_dir, os.path.basename(src)),
                  'wb') as f:
            f.write(data)

    @property
    def closed(self) -> bool:
        return self._closed

    def reopen(self) -> None:
        """Reopen a closed log over the same directory — the restart
        half of an in-process stop/restart cycle, and what
        ``ZKDatabase.recover_from_disk`` uses so collector-bound
        gauges and histograms (closures over THIS object) keep
        reading live state instead of a discarded instance.
        Cumulative counters (appends/fsyncs/sync_errors/snapshots)
        survive — they are process-lifetime metrics; positional state
        is re-derived from disk."""
        assert self._closed, 'reopen() is for a closed WAL'
        self._closed = False
        self._sync_scheduled = False
        self._inflight = False
        self._waiters = []
        self._sync_ewma_ms = None
        self._open_dir()

    def close(self) -> None:
        if self._closed:
            return
        if self.sync != 'never':
            self.sync_now()
        else:
            try:
                self._file.flush()
            except OSError:
                pass
        self._closed = True
        self._drain_waiters()        # gate reads closed -> released
        try:
            self._file.close()
        except OSError:
            pass


# ---------------------------------------------------------------------
# Database-level glue.
# ---------------------------------------------------------------------


def reset_dir(path: str) -> None:
    """Drop every segment and snapshot in a WAL directory — what a
    follower does when the leader bootstraps it from a snapshot
    despite its recovered state (the on-disk history is then stale
    relative to the installed image and must not be replayed over
    it).  Prefix-scoped on purpose: a co-tenant ``blackbox.*`` ring
    (utils/blackbox.py) records a history of the member, not of the
    tree — bootstrap must not erase it."""
    try:
        names = os.listdir(path)
    except FileNotFoundError:
        return
    for name in names:
        if (name.startswith(('wal.', 'snap.'))):
            try:
                os.unlink(os.path.join(path, name))
            except OSError:
                pass


def attach_wal(db, wal: WriteAheadLog) -> None:
    """Wire a log into a leader database: every committed txn is
    appended (store.py ``_commit``) before its ack can leave."""
    db.wal = wal
    wal.bind(db)


def restore_sequential_counters(tree) -> None:
    """Re-derive every parent's sequential counter from the node names
    it holds — what a follower promoted to leader (server/election.py)
    must do before allocating sequential names: its replica tree never
    consulted ``seq``, so the counters are all zero."""
    for path in list(tree.nodes):
        _advance_seq(tree, path)


def restore_sessions(db, sessions: dict) -> int:
    """Re-seat recovered sessions into a leader database: each gets a
    live :class:`~.store.ZKServerSession` with its ephemeral set
    rebuilt from the recovered tree and a FRESH expiry clock — a
    client that resumes inside the timeout keeps its session (and its
    ephemerals); one that never returns expires normally, and the
    expiry replays the ephemeral deletes as logged writes, exactly
    like real ZK's timeout-based expiry replay.  Outside a loop the
    clock stays unarmed until the first touch (unit-test contexts)."""
    from .store import ZKServerSession

    for sid, (passwd, timeout) in sessions.items():
        sess = ZKServerSession(id=sid, passwd=passwd, timeout=timeout)
        db.sessions[sid] = sess
    if sessions:
        for path, node in db.nodes.items():
            sess = db.sessions.get(node.ephemeral_owner) \
                if node.ephemeral_owner else None
            if sess is not None:
                sess.ephemerals.add(path)
        for sess in db.sessions.values():
            try:
                db.touch_session(sess)
            except RuntimeError:
                break                 # no loop: clocks start later
    return len(sessions)


def reap_orphan_ephemerals(db) -> int:
    """Delete recovered ephemerals whose owning session did NOT
    survive the crash — i.e. is absent from the recovered session
    table (closed/expired before the crash, or never durably
    created).  Sessions that *were* live stay live (restored with
    fresh expiry clocks by :func:`restore_sessions`), so their
    ephemerals survive a restart inside the session timeout; if the
    client never resumes, the normal expiry path reaps them by logged
    deletes.  The reaping deletes here are sequenced and logged like
    any write, so a second crash cannot resurrect them."""
    orphans = [p for p, n in db.nodes.items()
               if n.ephemeral_owner
               and n.ephemeral_owner not in db.sessions]
    for path in sorted(orphans, key=len, reverse=True):
        try:
            db.delete(path, -1)
        except Exception:
            log.warning('could not reap recovered ephemeral %s', path)
    return len(orphans)


def open_wal_database(path: str, *, sync: str = 'tick',
                      segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                      segment_age_s: float = DEFAULT_SEGMENT_AGE_S,
                      collector=None, faults=None, trace=None):
    """Recover (or initialize) a leader ``ZKDatabase`` from a WAL
    directory and attach a live log continuing it — the restart-from-
    disk entry point for ``ZKServer``, ``ZKEnsemble`` and the
    OS-process leader worker."""
    from .store import ZKDatabase

    rec = recover_state(path, trace=trace)
    db = ZKDatabase()
    db.nodes = rec.nodes
    db.zxid = rec.zxid
    db.epoch = rec.epoch
    db.log_start_zxid = rec.zxid
    if rec.config is not None:
        db.install_config(rec.config)
    wal = WriteAheadLog(path, sync=sync, segment_bytes=segment_bytes,
                        segment_age_s=segment_age_s,
                        collector=collector, faults=faults)
    attach_wal(db, wal)
    # sessions first: a recovered-live session keeps its ephemerals
    # (the restart-inside-timeout guarantee); only dead ones reap
    restore_sessions(db, rec.sessions)
    reap_orphan_ephemerals(db)
    return db


def scrape_wal_cells(collector) -> dict:
    """Summarize the WAL histograms for bench cells (`bench.py --wal`):
    fsync count + latency p50/p99, append count + bytes p50/p99."""
    out: dict = {}
    try:
        fs = collector.get_collector(METRIC_FSYNC)
        ap = collector.get_collector(METRIC_APPEND_BYTES)
    except ValueError:
        return out
    n = fs.count()
    if n:
        out['fsyncs'] = n
        out['fsync_p50_ms'] = round(fs.percentile(50), 3)
        out['fsync_p99_ms'] = round(fs.percentile(99), 3)
        out['fsync_mean_ms'] = round(fs.sum() / n, 3)
    m = ap.count()
    if m:
        out['appends'] = m
        out['append_p50_b'] = round(ap.percentile(50), 1)
        out['append_p99_b'] = round(ap.percentile(99), 1)
    return out
