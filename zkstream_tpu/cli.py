"""Command-line client: ``python -m zkstream_tpu <cmd> ...``.

The reference ecosystem's workflow leans on the Apache ``zkCli`` for
poking at a ZooKeeper tree (the reference's own tests shell out to it
for cross-validation, test/zkserver.js:72-164); this is the rebuild's
equivalent, built on the public ``Client``.

Commands: ls, get, set, create, delete, stat, getacl, sync, ping,
watch.  Exit status 0 on success, 1 on a ZooKeeper error (message on
stderr), 2 on usage errors.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys

from .client import Client
from .protocol.consts import CreateFlag
from .protocol.errors import ZKError, ZKProtocolError
from .protocol.records import Stat


def _parse_servers(value: str) -> list[dict]:
    """--server argument type: ``host[:port][,host[:port]...]`` with
    ``[v6addr]:port`` brackets; a bare IPv6 literal is a host.  Raises
    ArgumentTypeError (argparse usage error, exit 2) on bad specs."""
    servers = []
    for spec in value.split(','):
        spec = spec.strip()
        try:
            if spec.startswith('['):
                host, sep, rest = spec[1:].partition(']')
                if not sep or (rest and not rest.startswith(':')):
                    raise ValueError('bad [v6]:port syntax')
                port = int(rest[1:]) if rest else 2181
            elif spec.count(':') == 1:
                host, port_s = spec.split(':')
                port = int(port_s)
            elif spec.count(':') >= 2:
                # Only a genuine IPv6 literal may contain multiple
                # colons; anything else (host:2181:junk, a missing
                # comma) is a usage error, not a hostname.
                import ipaddress
                try:
                    ipaddress.IPv6Address(spec)
                except ValueError:
                    raise ValueError(
                        'multiple colons but not an IPv6 literal '
                        '(use [v6addr]:port, or a comma between specs)')
                host, port = spec, 2181
            else:  # bare hostname or IPv4
                host, port = spec, 2181
            if not host or not 0 < port < 65536:
                raise ValueError('empty host or port out of range')
        except ValueError as e:
            raise argparse.ArgumentTypeError(
                'invalid server spec %r: %s' % (spec, e))
        servers.append({'address': host, 'port': port})
    return servers


def _print_stat(stat: Stat) -> None:
    for name in Stat._fields:
        print('%s = %s' % (name, getattr(stat, name)))


async def _run(args) -> int:
    # Validate user arguments BEFORE connecting, with the same checks
    # the client API applies, so bad input is a clean exit-2 usage
    # error while later ValueErrors (e.g. a malformed server reply)
    # still surface as real errors.
    try:
        if getattr(args, 'path', None) is not None:
            Client._check_path(args.path)
        if getattr(args, 'version', None) is not None:
            Client._check_version(args.version)
    except (ValueError, TypeError) as e:
        print('usage error: %s' % (e,), file=sys.stderr)
        return 2

    addrs = ','.join('%s:%d' % (s['address'], s['port'])
                     for s in args.server)
    use_native = {'auto': None, 'native': True,
                  'python': False, 'ingest': None}[args.codec]
    ingest = None
    if args.codec == 'ingest':
        # the batched device plane with its production defaults
        # (measured bypass crossover, background warm) — CROSSOVER.md
        from .io.ingest import FleetIngest
        ingest = FleetIngest(body_mode='host')
    client = Client(servers=args.server,
                    session_timeout=args.session_timeout,
                    use_native_codec=use_native, ingest=ingest)
    client.start()
    try:
        try:
            await client.wait_connected(timeout=args.timeout)
        except (TimeoutError, asyncio.TimeoutError, ZKProtocolError):
            # timeout, or the pool exhausted its retry policy (failed)
            print('error: could not connect to %s' % (addrs,),
                  file=sys.stderr)
            return 1
        return await _dispatch(client, args)
    except (ZKError, ZKProtocolError) as e:
        print('error: %s (%s)' % (e.message, e.code), file=sys.stderr)
        return 1
    finally:
        await client.close()


async def _dispatch(client: Client, args) -> int:
    cmd = args.cmd
    if cmd == 'ping':
        latency = await client.ping()
        print('ping ok: %.1f ms' % (latency,))
    elif cmd == 'ls':
        children, stat = await client.list(args.path)
        for name in sorted(children):
            print(name)
        if args.stat:
            _print_stat(stat)
    elif cmd == 'get':
        data, stat = await client.get(args.path)
        out = sys.stdout.buffer
        out.write(data)
        if data and not data.endswith(b'\n'):
            out.write(b'\n')
        out.flush()
        if args.stat:
            _print_stat(stat)
    elif cmd == 'stat':
        _print_stat(await client.stat(args.path))
    elif cmd == 'getacl':
        from .protocol.consts import Perm
        for acl in await client.get_acl(args.path):
            # iterate the enum, not the flag value: Flag-member
            # iteration only exists on Python >= 3.11
            perms = '|'.join(sorted(
                p.name for p in Perm
                if p is not Perm.ALL and p in acl.perms))
            print('%s:%s = %s' % (acl.id.scheme, acl.id.id, perms))
    elif cmd == 'create':
        flags = CreateFlag(0)
        if args.ephemeral:
            flags |= CreateFlag.EPHEMERAL
        if args.sequential:
            flags |= CreateFlag.SEQUENTIAL
        data = args.data.encode() if args.data is not None else b''
        if args.parents:
            path = await client.create_with_empty_parents(
                args.path, data, flags=flags)
        else:
            path = await client.create(args.path, data, flags=flags)
        print(path)
        if args.ephemeral:
            # An ephemeral dies with its session: hold it until EOF so
            # the invocation is actually observable from elsewhere.  A
            # DAEMON thread (not the default executor) watches stdin so
            # ctrl-c exits promptly instead of hanging on executor join.
            print('holding ephemeral until EOF (ctrl-d) ...',
                  file=sys.stderr)
            import threading
            loop = asyncio.get_running_loop()
            eof: asyncio.Future = loop.create_future()

            def _stdin_eof():
                try:
                    sys.stdin.read()
                finally:
                    loop.call_soon_threadsafe(
                        lambda: eof.done() or eof.set_result(None))
            threading.Thread(target=_stdin_eof, daemon=True).start()
            await eof
    elif cmd == 'set':
        stat = await client.set(args.path, args.data.encode(),
                                version=args.version)
        print('version = %d' % (stat.version,))
    elif cmd == 'delete':
        await client.delete(args.path, args.version)
    elif cmd == 'sync':
        await client.sync(args.path)
    elif cmd == 'metrics':
        # one ping so the scrape is never empty of samples, then the
        # client collector's full Prometheus exposition (per-op
        # latency histograms, FSM transition counters, gauges)
        await client.ping()
        print(client.collector.expose())
    elif cmd == 'watch':
        return await _watch(client, args)
    else:  # pragma: no cover - argparse enforces choices
        raise AssertionError(cmd)
    return 0


async def _watch(client: Client, args) -> int:
    stop: asyncio.Future = asyncio.get_running_loop().create_future()
    seen = [0]

    def fire(evt):
        def cb(*a):
            extra = ''
            if evt == 'dataChanged' and a:
                extra = ' %r' % (bytes(a[0]),)
            elif evt == 'childrenChanged' and a:
                extra = ' %s' % (sorted(a[0]),)
            print('%s %s%s' % (evt, args.path, extra), flush=True)
            seen[0] += 1
            if args.count and seen[0] >= args.count and not stop.done():
                stop.set_result(None)
        return cb

    w = client.watcher(args.path)
    for evt in ('created', 'deleted', 'dataChanged', 'childrenChanged'):
        w.on(evt, fire(evt))
    client.on('expire', lambda *a: stop.done() or
              stop.set_exception(RuntimeError('session expired')))
    try:
        await stop
    except RuntimeError as e:
        print('error: %s' % (e,), file=sys.stderr)
        return 1
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog='python -m zkstream_tpu',
        description='ZooKeeper command-line client (zkstream_tpu)')
    p.add_argument('--server', '-s', type=_parse_servers,
                   default=[{'address': '127.0.0.1', 'port': 2181}],
                   help='host[:port][,host[:port]...]; [v6]:port for '
                        'IPv6 (default 127.0.0.1:2181)')
    p.add_argument('--session-timeout', type=int, default=30000,
                   help='ZK session timeout, ms')
    p.add_argument('--timeout', type=float, default=10.0,
                   help='connect timeout, seconds')
    p.add_argument('--codec',
                   choices=('auto', 'native', 'python', 'ingest'),
                   default='auto',
                   help='receive decoder: the C extension when built '
                        '(native: require it; python: scalar codec; '
                        'ingest: the batched device plane with its '
                        'production crossover; default auto)')
    sub = p.add_subparsers(dest='cmd', required=True)

    sub.add_parser('ping', help='round-trip a ping')

    ls = sub.add_parser('ls', help='list children')
    ls.add_argument('path')
    ls.add_argument('--stat', action='store_true',
                    help='also print the Stat')

    get = sub.add_parser('get', help='print node data')
    get.add_argument('path')
    get.add_argument('--stat', action='store_true')

    st = sub.add_parser('stat', help='print the Stat record')
    st.add_argument('path')

    ga = sub.add_parser('getacl', help='print the ACL list')
    ga.add_argument('path')

    cr = sub.add_parser('create', help='create a node')
    cr.add_argument('path')
    cr.add_argument('data', nargs='?', default=None)
    cr.add_argument('--ephemeral', '-e', action='store_true')
    cr.add_argument('--sequential', '-q', action='store_true')
    cr.add_argument('--parents', '-p', action='store_true',
                    help='create missing parents (persistent, b"null")')

    se = sub.add_parser('set', help='set node data')
    se.add_argument('path')
    se.add_argument('data')
    se.add_argument('--version', '-v', type=int, default=-1)

    de = sub.add_parser('delete', help='delete a node')
    de.add_argument('path')
    de.add_argument('--version', '-v', type=int, default=-1)

    sy = sub.add_parser('sync', help='sync a path with the leader')
    sy.add_argument('path')

    mn = sub.add_parser(
        'mntr',
        help='scrape a live server with a ZooKeeper four-letter '
             'admin word (raw TCP, no session)')
    mn.add_argument('word', nargs='?', default='mntr',
                    choices=('mntr', 'ruok', 'stat', 'srvr', 'trce'),
                    help='which admin word to send (default mntr; '
                         'trce dumps the member span ring as JSON)')

    rc = sub.add_parser(
        'reconfig',
        help='dynamic membership admin (README "Dynamic '
             'membership"): show or change the ensemble '
             'voter/observer sets at runtime over the rcfg admin '
             'channel (raw TCP, no session)')
    rc.add_argument('action', nargs='?', default='status',
                    choices=('status', 'propose', 'commit', 'apply'),
                    help='status scrapes every --server member; '
                         'propose lands the reconfig record (the '
                         'JOINT record for a voter change) and '
                         'stops; commit finishes an open joint '
                         'window; apply = propose + await joint '
                         'quorum + commit + await final quorum '
                         '(mutating actions walk --server until a '
                         'member answers as leader)')
    rc.add_argument('voters', nargs='?', default=None,
                    help='comma-separated member ids of the NEW '
                         'voter set (propose/apply)')
    rc.add_argument('observers', nargs='?', default=None,
                    help='comma-separated member ids of the new '
                         'observer set ("-" for none; default: '
                         'current observers minus any promoted '
                         'member)')

    tl = sub.add_parser(
        'timeline',
        help='render a merged zxid-ordered causal timeline: one '
             'traced write followed across client, leader (commit, '
             'WAL append, shared group-fsync span), followers '
             '(apply) and watch fan-out delivery.  Default: run a '
             'self-contained in-process ensemble demo; --live '
             'scrapes the member rings of the --server list (trce '
             'admin word) instead')
    tl.add_argument('--live', action='store_true',
                    help='scrape live members (--server) rather than '
                         'running the in-process demo')
    tl.add_argument('--members', type=int, default=3,
                    help='demo ensemble size (default 3)')
    tl.add_argument('--json', dest='as_json', action='store_true',
                    help='emit trace_schema-stamped JSON (rings + '
                         'merged timeline) instead of text')

    sub.add_parser(
        'metrics',
        help='connect, ping once, and print the client collector '
             'in Prometheus exposition format')

    wa = sub.add_parser('watch', help='stream watch events for a path')
    wa.add_argument('path')
    wa.add_argument('--count', '-n', type=int, default=0,
                    help='exit after N events (default: forever)')

    wl = sub.add_parser(
        'wal',
        help='dump/verify a write-ahead-log directory '
             '(server/persist.py): segment listing with CRC32C '
             'verification, snapshot inventory, truncation point, '
             'recovery summary — no server, no session')
    wl.add_argument('dir', help='WAL directory (ZKSTREAM_WAL_DIR / '
                                'ZKServer(wal_dir=))')
    wl.add_argument('--records', action='store_true',
                    help='also list every decoded record '
                         '(index, zxid, op, path, bytes)')

    bb = sub.add_parser(
        'blackbox',
        help='verify and render the flight-recorder rings in a WAL '
             'directory (utils/blackbox.py): per-member frame '
             'listing with CRC32C verification — a dead member\'s '
             'last mntr counters, tick phases, FSM census and span '
             'tail.  Torn final frame tolerated (the crash '
             'signature), bit flips rejected; no server, no session')
    bb.add_argument('dir', help='the member\'s wal_dir (the rings '
                                'are blackbox.<member>.log '
                                'co-tenants of the WAL)')
    bb.add_argument('--json', dest='as_json', action='store_true',
                    help='emit blackbox_schema-stamped JSON (every '
                         'frame) instead of the text summary')

    tp = sub.add_parser(
        'top',
        help='continuous fleet collector: poll mntr across every '
             '--server member, render live per-member deltas (role, '
             'epoch, config version, slow ops, quorum degradations) '
             'and optionally append a top_schema-stamped JSONL '
             'time-series — point-in-time scrapes become '
             'trajectories (works against OS-process members)')
    tp.add_argument('--interval', type=float, default=2.0,
                    help='seconds between polls (default 2)')
    tp.add_argument('--count', type=int, default=0,
                    help='stop after N polls (default: forever)')
    tp.add_argument('--out', metavar='PATH', default=None,
                    help='append one JSON line per member per poll '
                         '(top_schema-stamped) to PATH')

    an = sub.add_parser(
        'analyze',
        help='run the semantic static-analysis tier '
             '(zkstream_tpu/analysis/: loop-blocking, '
             'await-under-lock, span-leak, fault-order, knob/metric '
             'drift) and emit schema-stamped JSON findings — exit 1 '
             'when any exist, so chaos/CI harnesses consume it like '
             'wal/mntr.  No server, no session')
    an.add_argument('paths', nargs='*', default=None,
                    help='files/directories (default: the installed '
                         'zkstream_tpu package)')
    an.add_argument('--readme', default=None,
                    help='README to diff the knob/metric inventory '
                         'against (default: walk up from the first '
                         'target)')
    an.add_argument('--text', action='store_true',
                    help='human-readable findings instead of JSON')

    ch = sub.add_parser(
        'chaos',
        help='run seeded fault-injection schedules against an '
             'in-process server and verify the resilience invariants')
    ch.add_argument('--tier',
                    choices=('transport', 'ensemble', 'process'),
                    default='transport',
                    help='transport: byte/socket faults against one '
                         'server; ensemble: member kills/restarts, '
                         'replication partitions and session '
                         'migration with the history-checked '
                         'invariant engine (io/invariants.py); '
                         'process: OS-process peer members — seeded '
                         'elected-leader kill loops (each leader '
                         'SIGKILLed immediately after acking a '
                         'quorum-committed write, which must survive '
                         'the election) plus full-ensemble SIGKILL '
                         '-> election from recovered WALs '
                         '(server/election.py)')
    ch.add_argument('--seed', type=int, default=0,
                    help='base seed; schedule i uses seed+i (default 0)')
    ch.add_argument('--schedules', type=int, default=20,
                    help='number of consecutive seeded schedules')
    ch.add_argument('--ops', type=int, default=None,
                    help='client ops per schedule (default 6 for '
                         'transport, 12 plan steps for ensemble)')
    ch.add_argument('--quiet', action='store_true',
                    help='only print failing schedules + the summary')
    ch.add_argument('--no-watchtable', action='store_true',
                    help='rerun on the per-connection emitter '
                         'fallback instead of the sharded watch '
                         'fan-out table (server/watchtable.py) — '
                         'bisects whether a failing seed implicates '
                         'the table')
    ch.add_argument('--clients', type=int, default=None,
                    help='ensemble/process tiers: drive N CONCURRENT '
                         'clients over a small shared key set '
                         '(io/faults.py run_concurrent_schedule) and '
                         'check the two-sided history per key with '
                         'the WGL linearizability pass '
                         '(analysis/linearize.py, invariant 9).  '
                         'Part of the rerun key: seed + this flag '
                         'reproduce the schedule exactly.  Default: '
                         '1 (the classic single-client workload)')
    ch.add_argument('--observers', type=int, default=None,
                    help='ensemble/process tiers: attach N '
                         'non-voting observer members (the read '
                         'plane, README "Read plane") — clients run '
                         'with read distribution on, the observer '
                         'lag/partition fault vocabulary draws from '
                         'its own RNG stream, and the newly wired '
                         'session-monotone read check '
                         '(analysis/linearize.py '
                         'check_session_reads) is the invariant '
                         'under test.  Part of the rerun key like '
                         '--clients.  Default: drawn per seed '
                         '(ensemble tier) / 0 (process tier)')
    ch.add_argument('--overload', action='store_true',
                    help='force overload bursts into every schedule '
                         '(README "Overload plane"): the ensemble/'
                         'concurrent tiers draw forced pressure '
                         'steps — raw connection floods against the '
                         'admission caps + pacer, stalled client '
                         'readers (slow-consumer defense), and '
                         'oversized declared frames the member must '
                         'refuse with a definite close.  Part of '
                         'the rerun key like --clients.  Default: '
                         'drawn per seed')
    ch.add_argument('--cached', action='store_true',
                    help='ensemble/process tiers: run every '
                         "schedule's clients with the watch-backed "
                         'client cache on (README "Client cache '
                         'plane", io/cache.py cache="/"): reads are '
                         'served from the persistent-recursive-'
                         'watch-backed local cache whenever '
                         'coherent, and check_session_reads must '
                         'still hold on every locally served read '
                         '(a cached read can never time-travel). '
                         'Part of the rerun key like --clients.  '
                         'Default: drawn per seed (ensemble tier) / '
                         'off (process tier)')
    ch.add_argument('--reconfig', action='store_true',
                    help='force membership reconfigurations into '
                         'every schedule (README "Dynamic '
                         'membership"): the ensemble/concurrent '
                         'tiers draw forced reconfig steps (observer '
                         'join/leave, voter add/remove/replace with '
                         'joint-majority handoff; the first step is '
                         'always a voter replace), the process tier '
                         'drives a fenced voter replace per elected '
                         'era plus one full-ensemble SIGKILL '
                         'mid-joint recovered from WAL CONTROL '
                         'records.  Part of the rerun key like '
                         '--clients/--observers.  Default: drawn '
                         'per seed (ensemble tiers) / off (process)')
    ch.add_argument('--elections', type=int, default=None,
                    help='ensemble tier: force N leader elections '
                         'per schedule (kill the current leader at '
                         'evenly spaced steps; each must elect a '
                         'successor).  Part of the rerun key: seed + '
                         'this flag reproduce the schedule exactly. '
                         'Default: drawn per seed')
    ch.add_argument('--no-election', action='store_true',
                    help='rerun with the static member-0 leader '
                         '(ZKSTREAM_NO_ELECTION=1) — bisects whether '
                         'a failing seed implicates the election '
                         'plane (server/election.py)')
    ch.add_argument('--transport',
                    choices=('uring', 'mmsg', 'asyncio'),
                    default=None,
                    help='rerun on a forced transport backend '
                         '(io/transport.py; ZKSTREAM_TRANSPORT) — '
                         'bisects whether a failing seed implicates '
                         'the batched-syscall tier.  Forcing an '
                         'unavailable backend falls DOWN the '
                         'uring>mmsg>asyncio order, so the rerun '
                         'still executes (the summary names the '
                         'resolved backend)')
    ch.add_argument('--ingress-shards', type=int, default=None,
                    dest='ingress_shards', metavar='N',
                    help='rerun with a forced ingress shard count '
                         '(io/ingress.py; ZKSTREAM_INGRESS_SHARDS) — '
                         'part of the rerun key like --transport: '
                         'N>1 forces the sharded accept + batched '
                         'receive drain, 1 forces the single-loop '
                         'validator, so a failing seed bisects to '
                         'the ingress plane')
    ch.add_argument('--trace-out', metavar='PATH', default=None,
                    help='write every schedule\'s xid-correlated span '
                         'dump — member kill/restart events included '
                         'on the ensemble tier — as JSON to PATH for '
                         'offline triage')
    return p


async def _admin_one(host: str, port: int, word: str,
                     timeout: float) -> bytes:
    """One raw four-letter-word round trip; raises OSError/timeout."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), timeout)
    try:
        writer.write(word.encode('ascii'))
        await writer.drain()
        return await asyncio.wait_for(reader.read(), timeout)
    finally:
        writer.close()


async def _admin(args) -> int:
    """Send one four-letter admin word over raw TCP (no ZK session)
    to EVERY server in --server — an ensemble health probe scrapes
    each member, it does not stop at the first — and print the
    replies (prefixed by member when more than one).  Exit 0 when all
    answered, 1 when any was unreachable."""
    failed = 0
    many = len(args.server) > 1
    for spec in args.server:
        host, port = spec['address'], spec['port']
        if many:
            print('--- %s:%d ---' % (host, port))
        try:
            data = await _admin_one(host, port, args.word,
                                    args.timeout)
        except (OSError, asyncio.TimeoutError, TimeoutError):
            print('error: could not connect to %s:%d' % (host, port),
                  file=sys.stderr)
            failed += 1
            continue
        sys.stdout.write(data.decode('utf-8', 'replace'))
        if data and not data.endswith(b'\n'):
            sys.stdout.write('\n')
    return 1 if failed else 0


async def _reconfig(args) -> int:
    """Drive the ``rcfg`` dynamic-membership admin channel (README
    "Dynamic membership") over raw TCP — no ZK session, like the
    four-letter words.  ``status`` scrapes every --server member;
    the mutating actions (propose/commit/apply) walk the member list
    until one answers as leader, since only the leader may land
    CONTROL records."""
    if args.action in ('propose', 'apply') and not args.voters:
        print('error: %s needs a voter list (comma-separated '
              'member ids)' % (args.action,), file=sys.stderr)
        return 2
    line = args.action
    if args.voters:
        line += ' ' + args.voters
        if args.observers:
            line += ' ' + args.observers
    if args.action == 'status':
        failed = 0
        many = len(args.server) > 1
        for spec in args.server:
            host, port = spec['address'], spec['port']
            if many:
                print('--- %s:%d ---' % (host, port))
            try:
                reply = await _admin_one(host, port, 'rcfg status\n',
                                         args.timeout)
            except (OSError, asyncio.TimeoutError, TimeoutError):
                print('error: could not connect to %s:%d'
                      % (host, port), file=sys.stderr)
                failed += 1
                continue
            sys.stdout.write(reply.decode('utf-8', 'replace'))
        return 1 if failed else 0
    for spec in args.server:
        host, port = spec['address'], spec['port']
        try:
            reply = (await _admin_one(
                host, port, 'rcfg %s\n' % (line,),
                args.timeout)).decode('utf-8', 'replace')
        except (OSError, asyncio.TimeoutError, TimeoutError):
            continue
        if reply.startswith('error not leader'):
            continue
        sys.stdout.write(reply)
        return 1 if reply.startswith('error') else 0
    print('error: no member accepted %r (no reachable leader?)'
          % (line,), file=sys.stderr)
    return 1


async def _chaos(args) -> int:
    """Drive the seeded chaos campaign (io/faults.py) and report.
    Exit 0 when every schedule's invariants held, 1 otherwise; each
    line carries the seed, so any failure reruns with --seed N
    (--tier ensemble for the failover tier) — and arrives with its
    xid-correlated span dump (utils/trace.py) plus, on the ensemble
    tier, the member-event timeline, so the failing interleaving is
    visible without log grepping."""
    from .io.faults import run_campaign, run_ensemble_campaign
    from .io.invariants import format_history
    from .utils.trace import (
        TRACE_SCHEMA,
        format_spans,
        format_timeline,
        merge_timelines,
    )

    if getattr(args, 'no_watchtable', False):
        # the schedule servers resolve their dispatch path from the
        # env at construction, exactly like the cork/codec tiers
        os.environ['ZKSTREAM_NO_WATCHTABLE'] = '1'
    if getattr(args, 'no_election', False):
        os.environ['ZKSTREAM_NO_ELECTION'] = '1'
    if getattr(args, 'transport', None):
        # the schedule servers/clients resolve their backend from the
        # env at construction (io/transport.py); part of the rerun key
        os.environ['ZKSTREAM_TRANSPORT'] = args.transport
        from .io.transport import backend_default
        print('# transport backend forced: %s (resolved: %s)'
              % (args.transport, backend_default()))
    if getattr(args, 'ingress_shards', None):
        # the schedule servers resolve their receive path from the
        # env at construction (io/ingress.py); part of the rerun key
        os.environ['ZKSTREAM_INGRESS_SHARDS'] = \
            str(args.ingress_shards)
        from .io.ingress import backend_default as rx_default
        print('# ingress shards forced: %d (backend: %s)'
              % (args.ingress_shards,
                 rx_default() if args.ingress_shards > 1
                 else 'asyncio'))

    def progress(r):
        if args.quiet and r.ok:
            return
        status = 'ok ' if r.ok else 'FAIL'
        print('seed %6d  %s  ops=%d acked=%d typed_errs=%d '
              'deadline=%d faults=%d watch_fires=%d%s%s%s'
              % (r.seed, status, r.ops, r.acked, r.typed_errors,
                 r.deadline_errors, r.faults, r.watch_fires,
                 '' if r.tier == 'transport'
                 else ' member_events=%d' % (len(r.member_events),),
                 '' if not r.elections
                 else ' elections=%d' % (r.elections,),
                 '' if r.clients <= 1
                 else ' clients=%d' % (r.clients,)))
        for v in r.violations:
            print('    violation: %s' % (v,))
        if not r.ok and r.history:
            timeline = format_history(r.history)
            if timeline:
                print('  member-event timeline:')
                print(timeline)
            if any(rec['kind'] == 'invoke' for rec in r.history):
                # the concurrent tier: a linearizability
                # counterexample window (in the violations above) is
                # read against the per-client interleaving
                print('  per-client interleaving:')
                print(format_history(r.history, columns=True))
        if not r.ok and r.trace:
            print('  span ring (oldest first):')
            print(format_spans(r.trace))
        if not r.ok and (r.trace or r.member_rings):
            # the cross-member view: client + member rings merged by
            # zxid, so the violated write's full causal path (commit,
            # fsync barrier, replication, follower apply, fan-out) is
            # on screen next to the seed
            merged = merge_timelines(
                dict({'client': r.trace}, **r.member_rings))
            if merged:
                print('  merged causal timeline (zxid order):')
                print(format_timeline(merged, limit=60))

    if args.tier == 'ensemble':
        results = await run_ensemble_campaign(
            args.seed, args.schedules,
            ops=args.ops if args.ops is not None else 12,
            progress=progress,
            elections=getattr(args, 'elections', None),
            clients=getattr(args, 'clients', None),
            observers=getattr(args, 'observers', None),
            # --reconfig forces two steps per schedule; the FIRST
            # executed step is always a voter replace (io/faults.py),
            # so every campaign holds >= 1 joint-majority handoff
            reconfigs=2 if getattr(args, 'reconfig', False) else None,
            # --overload likewise forces two pressure bursts per
            # schedule (flood / stalled reader / oversized frame)
            overloads=2 if getattr(args, 'overload', False)
            else None,
            # --cached forces the watch-backed client cache on for
            # every schedule (default: drawn per seed)
            cached=True if getattr(args, 'cached', False) else None)
    elif args.tier == 'process':
        if getattr(args, 'no_election', False):
            # the process tier IS the election plane: there is no
            # static-leader variant of symmetric peers to bisect to
            print('error: --no-election has no meaning on the '
                  'process tier (symmetric peers have no static '
                  'leader); use --tier ensemble', file=sys.stderr)
            return 2
        if getattr(args, 'overload', False):
            print('error: --overload runs on the in-process '
                  'ensemble tier; use --tier ensemble',
                  file=sys.stderr)
            return 2
        from .server.election import run_process_campaign
        results = await run_process_campaign(
            args.seed, args.schedules,
            ops=args.ops if args.ops is not None else 6,
            progress=progress,
            elections=getattr(args, 'elections', None),
            clients=getattr(args, 'clients', None),
            observers=getattr(args, 'observers', None),
            reconfig=getattr(args, 'reconfig', False),
            cached=getattr(args, 'cached', False))
    else:
        if getattr(args, 'clients', None) and args.clients > 1:
            print('error: --clients needs the history-checked '
                  'tiers; use --tier ensemble or --tier process',
                  file=sys.stderr)
            return 2
        if getattr(args, 'observers', None):
            print('error: --observers needs an ensemble; use '
                  '--tier ensemble or --tier process',
                  file=sys.stderr)
            return 2
        if getattr(args, 'reconfig', False):
            print('error: --reconfig needs an ensemble; use '
                  '--tier ensemble or --tier process',
                  file=sys.stderr)
            return 2
        if getattr(args, 'overload', False):
            print('error: --overload needs an ensemble; use '
                  '--tier ensemble (the transport tier draws its '
                  'own overload slice per seed)', file=sys.stderr)
            return 2
        if getattr(args, 'cached', False):
            print('error: --cached needs the history-checked '
                  'tiers (check_session_reads is what holds the '
                  'cache coherent); use --tier ensemble or --tier '
                  'process', file=sys.stderr)
            return 2
        results = await run_campaign(
            args.seed, args.schedules,
            ops=args.ops if args.ops is not None else 6,
            progress=progress)
    if args.trace_out:
        import json
        with open(args.trace_out, 'w') as f:
            # member kill/restart events ride the span ring (kind
            # 'member') AND the structured history; bytes payloads in
            # history records serialize via repr.  Each schedule is
            # schema-stamped and carries every member's server-side
            # ring plus the merged zxid-ordered timeline.
            json.dump([{'trace_schema': TRACE_SCHEMA,
                        'seed': r.seed, 'ok': r.ok, 'tier': r.tier,
                        'violations': r.violations,
                        'member_events': r.member_events,
                        'trace': r.trace,
                        'member_rings': r.member_rings,
                        'timeline': merge_timelines(
                            dict({'client': r.trace},
                                 **r.member_rings)),
                        'history': r.history}
                       for r in results], f, indent=2, default=repr)
        print('span dumps written to %s' % (args.trace_out,))
    bad = [r for r in results if not r.ok]
    print('%d/%d schedules ok (%d faults injected, %d typed errors, '
          '%d deadline errors)'
          % (len(results) - len(bad), len(results),
             sum(r.faults for r in results),
             sum(r.typed_errors for r in results),
             sum(r.deadline_errors for r in results)))
    if bad:
        clients = getattr(args, 'clients', None)
        observers = getattr(args, 'observers', None)
        print('failing seeds (rerun: python -m zkstream_tpu chaos '
              '--tier %s%s%s%s%s --seed N --schedules 1): %s'
              % (args.tier,
                 ' --clients %d' % (clients,)
                 if clients and clients > 1 else '',
                 ' --observers %d' % (observers,)
                 if observers else '',
                 ' --reconfig'
                 if getattr(args, 'reconfig', False) else '',
                 ' --overload'
                 if getattr(args, 'overload', False) else '',
                 ', '.join(str(r.seed) for r in bad)),
              file=sys.stderr)
        return 1
    return 0


async def _timeline(args) -> int:
    """The causal-timeline renderer.  Demo mode runs a 3-member
    in-process ensemble (WAL on, watch armed), performs one traced
    write, and prints the merged client+member timeline — the span
    chain README "Causal tracing" documents.  ``--live`` scrapes the
    ``trce`` admin word from every --server member (an OS-process
    ensemble included) and merges whatever rings they hold."""
    import json as _json

    from .utils.trace import (
        TRACE_SCHEMA,
        format_timeline,
        merge_timelines,
    )

    if args.live:
        rings: dict = {}
        dropped: dict = {}
        failed = 0
        for spec in args.server:
            host, port = spec['address'], spec['port']
            try:
                raw = await _admin_one(host, port, 'trce',
                                       args.timeout)
                dump = _json.loads(raw.decode('utf-8'))
            except (OSError, ValueError, asyncio.TimeoutError,
                    TimeoutError):
                print('error: could not scrape trce from %s:%d'
                      % (host, port), file=sys.stderr)
                failed += 1
                continue
            key = 'member:%s' % (dump.get('member', port),)
            if key in rings:
                # two members reporting the same id (e.g. two
                # standalone servers, both default '0'): qualify by
                # address rather than silently overwriting one ring
                key = 'member:%s@%s:%d' % (dump.get('member', port),
                                           host, port)
            rings[key] = dump.get('spans', [])
            # the ring is bounded: a wrapped ring silently lost spans
            # before this scrape — surface the count next to the ring
            # (the zk_trace_ring_dropped mntr row, per member)
            dropped[key] = dump.get('dropped', 0)
        if failed and not rings:
            return 1
        merged = merge_timelines(rings)
        if args.as_json:
            print(_json.dumps({'trace_schema': TRACE_SCHEMA,
                               'rings': rings, 'dropped': dropped,
                               'timeline': merged},
                              indent=2))
        else:
            for key in sorted(rings):
                print('# %s: %d span(s), %d dropped (ring '
                      'overwrites)' % (key, len(rings[key]),
                                       dropped.get(key, 0)))
            print(format_timeline(merged) or '(no zxid-keyed spans)')
        return 1 if failed else 0

    # -- demo: in-process ensemble, one write, full span chain --------
    import shutil
    import tempfile

    from .server.server import ZKEnsemble

    loop = asyncio.get_running_loop()
    wal_dir = tempfile.mkdtemp(prefix='zktimeline-wal-')
    ens = await ZKEnsemble(max(2, args.members),
                           wal_dir=wal_dir).start()
    client = Client(servers=[{'address': h, 'port': p}
                             for h, p in ens.addresses()],
                    shuffle_backends=False)
    client.start()
    try:
        await client.wait_connected(timeout=10)
        await client.create('/demo', b'v0')
        fires: list = []
        fired = loop.create_future()

        def on_change(*a):
            fires.append(a)
            if len(fires) >= 2 and not fired.done():
                fired.set_result(None)   # arm-time emit + the real one
        client.watcher('/demo').on('dataChanged', on_change)
        await asyncio.sleep(0.2)         # watch armed, arm-emit in
        await client.set('/demo', b'v1')
        await asyncio.wait_for(fired, 10)
        await client.sync('/demo')       # drain fan-out + fsync legs
        await asyncio.sleep(0.05)
        rings = {'client': client.trace.dump()}
        for s in ens.servers:
            if s.trace is not None:
                rings['member:%s' % (s.member,)] = s.trace.dump()
        merged = merge_timelines(rings)
        if args.as_json:
            print(_json.dumps({'trace_schema': TRACE_SCHEMA,
                               'rings': rings, 'timeline': merged},
                              indent=2))
        else:
            print('causal timeline for one create + one watched set '
                  '(%d members, WAL on):' % (len(ens.servers),))
            print(format_timeline(merged))
        return 0
    finally:
        await client.close()
        await ens.stop()
        shutil.rmtree(wal_dir, ignore_errors=True)


def _wal(args) -> int:
    """Dump/verify a WAL directory through the same scan recovery
    uses (server/persist.py scan_dir), so the CLI and the recovery
    path can never disagree on what is valid.  Exit 0 when the
    directory is recoverable (a torn *final* record is the normal
    crash signature and is tolerated, like recovery tolerates it);
    exit 1 on structural corruption — a mid-log CRC/decode failure or
    an invalid snapshot with nothing to fall back to."""
    from .server.persist import entry_zxid, recover_state, scan_dir

    scan = scan_dir(args.dir)
    if not scan.segments and not scan.snapshots:
        print('no WAL state in %s' % (args.dir,), file=sys.stderr)
        return 1
    print('wal dir: %s' % (args.dir,))
    print('segments:')
    corrupt = 0
    for i, seg in enumerate(scan.segments):
        last = i == len(scan.segments) - 1
        if seg.status == 'ok':
            note = 'ok'
        else:
            note = '%s@%d (%s)' % (seg.status, seg.valid_bytes,
                                   seg.error)
            # a torn tail on the FINAL segment is what dying
            # mid-write leaves; anything else is real corruption
            if not (last and seg.status in ('torn', 'crc')):
                corrupt += 1
        print('  %-28s start=%-6d records=%-5d bytes=%-8d %s'
              % (os.path.basename(seg.path), seg.start_index,
                 len(seg.records), seg.size, note))
        if args.records:
            for idx, entry in seg.records:
                extra = ('' if entry[0] != 'create'
                         else ' data=%dB' % (len(entry[2]),))
                # control records carry no path: epoch bumps hold the
                # fencing token (server/election.py), session records
                # the durable session edge (server/persist.py), and a
                # multi renders its whole all-or-nothing batch
                if entry[0] == 'epoch':
                    what = 'epoch=%d' % (entry[1],)
                elif entry[0] == 'session':
                    what = ('sid=%016x timeout=%dms'
                            % (entry[1], entry[3]))
                elif entry[0] == 'session_close':
                    what = 'sid=%016x (%s)' % (entry[1], entry[3])
                elif entry[0] == 'multi':
                    what = '%d sub-op(s): %s' % (
                        len(entry[1]),
                        ', '.join('%s %s' % (s[0], s[1])
                                  for s in entry[1]))
                elif entry[0] == 'reconfig':
                    # the membership CONTROL record: a surviving
                    # 'joint' with old_voters IS the crash-mid-window
                    # signature recovery resumes from
                    what = 'version=%d phase=%s voters=%s' % (
                        entry[1], entry[2],
                        ','.join(str(m) for m in entry[4]) or '-')
                    if entry[3]:
                        what += ' old_voters=%s' % (
                            ','.join(str(m) for m in entry[3]),)
                    if entry[5]:
                        what += ' observers=%s' % (
                            ','.join(str(m) for m in entry[5]),)
                else:
                    what = entry[1]
                print('    #%-6d zxid=%-6d %-8s %s%s'
                      % (idx, entry_zxid(entry), entry[0], what,
                         extra))
    print('snapshots:')
    if not scan.snapshots:
        print('  (none)')
    for snap in scan.snapshots:
        if snap.valid:
            print('  %-28s index=%-6d zxid=%-6d nodes=%-5d ok'
                  % (os.path.basename(snap.path), snap.index,
                     snap.zxid, len(snap.nodes)))
        else:
            print('  %-28s INVALID (%s)'
                  % (os.path.basename(snap.path), snap.error))
    newest = scan.newest_valid_snapshot()
    if any(not s.valid for s in scan.snapshots) and newest is None \
            and scan.snapshots:
        corrupt += 1
    if newest is not None:
        print('truncation point: index %d (zxid %d) — segments '
              'wholly below the oldest kept snapshot are reclaimable'
              % (newest.index, newest.zxid))
    rec = recover_state(args.dir)
    print('recovery: %s -> zxid %d (next index %d)'
          % (rec.detail, rec.zxid, rec.last_index))
    if corrupt:
        print('status: STRUCTURAL CORRUPTION (%d finding(s)); '
              'recovery stops at the last valid prefix' % (corrupt,),
              file=sys.stderr)
        return 1
    print('status: clean%s'
          % (' (torn final record tolerated)' if rec.torn else ''))
    return 0


def _blackbox(args) -> int:
    """Verify/render the flight-recorder rings of a WAL directory
    through the same scan recovery uses (utils/blackbox.py
    ``read_box``), so the CLI and the harvest path can never disagree
    on what is valid.  Exit 0 when every ring is recoverable (a torn
    FINAL frame is the normal crash signature and is tolerated); exit
    1 on structural corruption (a CRC failure, a torn rotated half)
    or when the directory holds no rings at all."""
    import json as _json

    from .utils.blackbox import BLACKBOX_SCHEMA, list_boxes, read_box

    members = list_boxes(args.dir)
    if not members:
        print('no black-box rings in %s' % (args.dir,),
              file=sys.stderr)
        return 1
    corrupt = 0
    out = []
    for member in members:
        box = read_box(args.dir, member)
        if box['status'] not in ('ok', 'torn'):
            corrupt += 1
        out.append(box)
    if args.as_json:
        print(_json.dumps({
            'blackbox_schema': BLACKBOX_SCHEMA,
            'dir': args.dir,
            'members': [{
                'member': b['member'], 'status': b['status'],
                'files': [{'path': os.path.basename(f.path),
                           'status': f.status, 'error': f.error,
                           'frames': len(f.frames),
                           'valid_bytes': f.valid_bytes,
                           'size': f.size} for f in b['files']],
                'frames': b['frames'],
            } for b in out]}, indent=2))
        return 1 if corrupt else 0
    print('blackbox dir: %s' % (args.dir,))
    for box in out:
        print('member %s: %d frame(s), status %s'
              % (box['member'], len(box['frames']), box['status']))
        for f in box['files']:
            note = 'ok' if f.status == 'ok' else (
                '%s@%d (%s)' % (f.status, f.valid_bytes, f.error))
            print('  %-28s frames=%-5d bytes=%-8d %s'
                  % (os.path.basename(f.path), len(f.frames),
                     f.size, note))
        for fr in box['frames']:
            mntr = fr.get('mntr') or {}
            slow = fr.get('slow')
            extra = ''
            if fr.get('phases'):
                extra += ' phases=%d' % (len(fr['phases']),)
            if fr.get('trace_tail') is not None:
                extra += ' spans=%d' % (len(fr['trace_tail']),)
            if slow is not None:
                extra += ' slow=%s %.1fms chain=%d' % (
                    slow.get('op'), slow.get('duration_ms') or 0.0,
                    len(fr.get('chain') or ()))
            print('    #%-5d %-8s role=%-9s zxid=%-8s slow_ops=%-4s'
                  '%s'
                  % (fr.get('seq', -1), fr.get('kind'),
                     mntr.get('zk_member_role', '-'),
                     mntr.get('zk_zxid', '-'),
                     mntr.get('zk_slow_ops_total', '-'), extra))
    if corrupt:
        print('status: STRUCTURAL CORRUPTION (%d ring(s)); harvest '
              'stops at each last valid prefix' % (corrupt,),
              file=sys.stderr)
        return 1
    torn = any(b['status'] == 'torn' for b in out)
    print('status: clean%s'
          % (' (torn final frame tolerated)' if torn else ''))
    return 0


def _parse_mntr_text(text: str) -> dict:
    """mntr reply lines ('key\\tvalue') to a dict, values coerced to
    int/float where they parse."""
    rows: dict = {}
    for line in text.splitlines():
        if '\t' not in line:
            continue
        key, _, val = line.partition('\t')
        for conv in (int, float):
            try:
                rows[key] = conv(val)
                break
            except ValueError:
                continue
        else:
            rows[key] = val
    return rows


async def _top(args) -> int:
    """The continuous fleet collector: one mntr scrape per member per
    interval, per-member delta rendering, optional JSONL append
    (top_schema-stamped, one line per member per poll) — the
    trajectory view the point-in-time words cannot give.  Exit 0 once
    stopped (--count or ctrl-c) if any member ever answered."""
    import json as _json
    import time as _time

    from .utils.blackbox import TOP_SCHEMA

    #: counters whose per-interval delta is the interesting number
    deltas = ('zk_packets_received', 'zk_packets_sent',
              'zk_slow_ops_total', 'zk_quorum_degraded',
              'zk_blackbox_frames', 'zk_trace_ring_dropped')
    prev: dict = {}
    ever = 0
    polls = 0
    out_f = open(args.out, 'a') if args.out else None
    try:
        while True:
            stamp = _time.strftime('%H:%M:%S')
            for spec in args.server:
                host, port = spec['address'], spec['port']
                who = '%s:%d' % (host, port)
                try:
                    raw = await _admin_one(host, port, 'mntr',
                                           args.timeout)
                    rows = _parse_mntr_text(
                        raw.decode('utf-8', 'replace'))
                except (OSError, asyncio.TimeoutError,
                        TimeoutError):
                    print('%s %-21s unreachable' % (stamp, who))
                    continue
                ever += 1
                last = prev.get(who) or {}
                moved = []
                for key in deltas:
                    cur = rows.get(key)
                    if not isinstance(cur, (int, float)):
                        continue
                    base = last.get(key)
                    d = (cur - base
                         if isinstance(base, (int, float)) else cur)
                    moved.append('%s+%g'
                                 % (key.replace('zk_', ''), d))
                prev[who] = rows
                print('%s %-21s %-9s epoch=%-3s cfg=%-3s '
                      'zxid=%-10s conns=%-5s %s'
                      % (stamp, who,
                         rows.get('zk_member_role', '?'),
                         rows.get('zk_epoch', '?'),
                         rows.get('zk_config_version', '-'),
                         rows.get('zk_zxid', '?'),
                         rows.get('zk_num_alive_connections', '?'),
                         ' '.join(moved)))
                if out_f is not None:
                    out_f.write(_json.dumps({
                        'top_schema': TOP_SCHEMA,
                        't_wall': round(_time.time(), 3),
                        'member': who,
                        'mntr': rows}) + '\n')
            if out_f is not None:
                out_f.flush()
            polls += 1
            if args.count and polls >= args.count:
                break
            await asyncio.sleep(args.interval)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        if out_f is not None:
            out_f.close()
    return 0 if ever else 1


def _analyze(args) -> int:
    """The contract-lint tier as a subcommand: JSON findings with
    file:line positions (schema-stamped, like every other machine
    emission), exit 1 on findings — the gate `make analyze` wires
    into `make check`, consumable by CI without parsing text."""
    from .analysis import analyze_paths

    paths = args.paths or [os.path.dirname(os.path.abspath(
        __file__))]
    report = analyze_paths(paths, readme_path=args.readme)
    if args.text:
        for f in report.findings:
            print(f.format())
        print('%d file(s) analyzed, %d finding(s)'
              % (report.nfiles, len(report.findings)))
    else:
        print(report.to_json(indent=2))
    return 1 if report.findings else 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == 'analyze':
        # offline AST analysis: no server, no event loop
        return _analyze(args)
    if args.cmd == 'chaos':
        # chaos runs its own in-process servers; no --server dial.
        return asyncio.run(_chaos(args))
    if args.cmd == 'wal':
        # offline directory inspection: no server, no event loop
        return _wal(args)
    if args.cmd == 'blackbox':
        # offline flight-recorder inspection: no server, no loop
        return _blackbox(args)
    if args.cmd == 'top':
        # raw mntr polling loop: no client, no session
        return asyncio.run(_top(args))
    if args.cmd == 'mntr':
        # raw four-letter-word scrape: no client, no session
        return asyncio.run(_admin(args))
    if args.cmd == 'reconfig':
        # raw rcfg admin line: no client, no session
        return asyncio.run(_reconfig(args))
    if args.cmd == 'timeline':
        # self-contained demo (or raw trce scrape with --live):
        # never dials --server as a protocol client
        return asyncio.run(_timeline(args))
    return asyncio.run(_run(args))


if __name__ == '__main__':  # pragma: no cover - exercised via __main__
    sys.exit(main())
