"""A Moore-machine FSM base with auto-disposing state scopes.

The reference builds every stateful component (client, connection,
session, watch events) on the mooremachine library's pattern: each state
is a ``state_<name>`` method receiving a scope handle ``S``; listeners
and timers registered through ``S`` are torn down automatically on the
next transition.  That discipline is what makes the protocol's many
races tractable, so this module provides the same contract for asyncio:

- ``goto_state(name)`` disposes the current scope (listeners removed,
  timers cancelled) and runs ``state_<name>(S)``;
- ``S.on(emitter, event, cb)`` / ``S.timeout(ms, cb)`` /
  ``S.interval(ms, cb)`` / ``S.immediate(cb)`` are scope-bound;
- dotted substates (``armed.doublecheck``) keep the parent state's scope
  alive, inheriting its transitions, exactly like mooremachine substates
  (reference: lib/zk-session.js:671-673);
- ``is_in_state('armed')`` is true while in ``armed.doublecheck``;
- every transition emits ``stateChanged`` with the new state name.
"""

from __future__ import annotations

import asyncio
import weakref
from typing import Callable

from .events import EventEmitter
from .aio import ambient_loop

METRIC_FSM_TRANSITIONS = 'zkstream_fsm_transitions'
METRIC_FSM_STATE = 'zkstream_fsm_state'


def _fsm_state_counts(registry) -> dict:
    """Current-state census over a weak registry of instrumented
    machines: {labels: count of live machines in that state}."""
    counts: dict[tuple[str, str], int] = {}
    for machine in list(registry):
        label = getattr(machine, '_fsm_metrics_label', None)
        state = machine.get_state()
        if label is None or not state:
            continue
        counts[(label, state)] = counts.get((label, state), 0) + 1
    return {(('fsm', label), ('state', state)): float(n)
            for (label, state), n in counts.items()}


def bind_transition_metrics(machine, collector,
                            label: str | None = None) -> None:
    """Instrument any object with a ``get_state()`` and state
    transitions (FSM subclasses get the counting for free via
    ``FSM._transition``; the pool calls :func:`note_transition`
    manually) so ``collector`` exposes:

    - ``zkstream_fsm_transitions{fsm,from,to}`` — a counter bumped on
      every transition;
    - ``zkstream_fsm_state{fsm,state}`` — a pull gauge counting live
      machines per (label, state) at scrape time.

    The registry holds weak references, so instrumented machines are
    censused only while alive; binding is idempotent per collector
    (the counter is fetched, the gauge registered once)."""
    if label is None:
        label = type(machine).__name__
    machine._fsm_metrics_ctr = collector.counter(
        METRIC_FSM_TRANSITIONS, 'FSM state transitions')
    machine._fsm_metrics_label = label
    registry = getattr(collector, '_fsm_registry', None)
    if registry is None:
        registry = collector._fsm_registry = weakref.WeakSet()
        collector.multi_gauge(
            METRIC_FSM_STATE,
            lambda reg=registry: _fsm_state_counts(reg),
            'Live state machines per (fsm, state)')
    registry.add(machine)


def note_transition(machine, old: str | None, new: str) -> None:
    """Count one state transition on the machine's bound collector
    (no-op until :func:`bind_transition_metrics` ran)."""
    ctr = getattr(machine, '_fsm_metrics_ctr', None)
    if ctr is not None:
        ctr.increment({'fsm': machine._fsm_metrics_label,
                       'from': old or '', 'to': new})


class StateScope:
    """Handle passed to ``state_*`` methods; everything registered through
    it is disposed when the machine leaves the state."""

    def __init__(self, fsm: 'FSM', state: str):
        self._fsm = fsm
        self._state = state
        self._disposers: list[Callable[[], None]] = []
        self._valid = True

    def on(self, emitter: EventEmitter, event: str, cb: Callable) -> None:
        def guarded(*args):
            if self._valid:
                cb(*args)
        emitter.on(event, guarded)
        self._disposers.append(
            lambda: emitter.remove_listener(event, guarded))

    def timeout(self, ms: float,
                cb: Callable[[], None]) -> asyncio.TimerHandle:
        loop = ambient_loop()
        handle = loop.call_later(ms / 1000.0,
                                 lambda: self._valid and cb())
        self._disposers.append(handle.cancel)
        return handle

    def interval(self, ms: float, cb: Callable[[], None]) -> None:
        loop = ambient_loop()
        state = {}

        def fire():
            if not self._valid:
                return
            cb()
            if self._valid:
                state['h'] = loop.call_later(ms / 1000.0, fire)

        state['h'] = loop.call_later(ms / 1000.0, fire)
        self._disposers.append(lambda: state['h'].cancel())

    def immediate(self, cb: Callable[[], None]) -> None:
        loop = ambient_loop()
        handle = loop.call_soon(lambda: self._valid and cb())
        self._disposers.append(handle.cancel)

    def defer(self, cb: Callable[[], None]) -> None:
        """Run ``cb`` when the machine leaves this state (scope-exit
        cleanup, e.g. deregistering from an external registry)."""
        self._disposers.append(cb)

    def goto_state(self, name: str) -> None:
        if self._valid:
            self._fsm._transition(name)

    def _dispose(self) -> None:
        self._valid = False
        for d in self._disposers:
            d()
        self._disposers.clear()


class FSM(EventEmitter):
    """Base class: subclasses define ``state_<name>(self, S)`` methods and
    call ``super().__init__(initial_state)``."""

    def __init__(self, initial: str):
        super().__init__()
        self._state: str | None = None
        #: Scope stack: one entry per dotted level of the current state
        #: (['armed'] or ['armed', 'armed.doublecheck']).
        self._scopes: list[tuple[str, StateScope]] = []
        self._in_transition = False
        self._queued: str | None = None
        self._transition(initial)

    def get_state(self) -> str:
        return self._state or ''

    def is_in_state(self, name: str) -> bool:
        if self._state is None:
            return False
        return self._state == name or self._state.startswith(name + '.')

    def bind_fsm_metrics(self, collector, label: str | None = None) \
            -> None:
        """Expose this machine's transitions/current state on
        ``collector`` (see :func:`bind_transition_metrics`).  Called
        before ``super().__init__`` the initial transition is counted
        too; after, counting starts from the next transition."""
        bind_transition_metrics(self, collector, label)

    def _transition(self, name: str) -> None:
        # A transition triggered from inside a state_* entry function is
        # deferred until the entry function returns (mooremachine allows
        # synchronous re-entry; a queue keeps the bookkeeping sane).
        if self._in_transition:
            self._queued = name
            return

        # Dispose scopes that are not parents of the new state.  Entering
        # 'armed.doublecheck' from 'armed' keeps the 'armed' scope alive;
        # entering 'wait_session' from 'armed.doublecheck' disposes both.
        keep = 0
        parts = name.split('.')
        prefixes = ['.'.join(parts[:i + 1]) for i in range(len(parts) - 1)]
        for st, _scope in self._scopes:
            if keep < len(prefixes) and st == prefixes[keep]:
                keep += 1
            else:
                break
        for _st, scope in reversed(self._scopes[keep:]):
            scope._dispose()
        del self._scopes[keep:]

        handler = getattr(self, 'state_' + name.replace('.', '_'), None)
        if handler is None:
            raise AttributeError('%s has no state %r' %
                                 (type(self).__name__, name))
        scope = StateScope(self, name)
        self._scopes.append((name, scope))
        note_transition(self, self._state, name)
        self._state = name
        self._in_transition = True
        try:
            handler(scope)
        finally:
            self._in_transition = False
        self.emit('stateChanged', name)
        if self._queued is not None:
            nxt, self._queued = self._queued, None
            self._transition(nxt)
