"""A small synchronous event emitter.

The connection/session/watcher layers are event-driven state machines;
this provides Node-style ``on``/``once``/``emit`` dispatch semantics for
them: listeners run synchronously in registration order, and a listener
removed mid-dispatch (e.g. by a state transition disposing its scope) is
not called for that emit.
"""

from __future__ import annotations

import logging
from typing import Any, Callable


class EventEmitter:
    def __init__(self) -> None:
        self._listeners: dict[str, list[Callable]] = {}
        #: bumped on every registry mutation; lets emit() skip the
        #: per-callback liveness checks when nothing changed mid-dispatch
        self._ver = 0

    def on(self, event: str, cb: Callable) -> 'EventEmitter':
        self._listeners.setdefault(event, []).append(cb)
        self._ver += 1
        return self

    def once(self, event: str, cb: Callable) -> 'EventEmitter':
        def wrapper(*args: Any) -> None:
            self.remove_listener(event, wrapper)
            cb(*args)
        wrapper.__wrapped__ = cb  # type: ignore[attr-defined]
        self._listeners.setdefault(event, []).append(wrapper)
        self._ver += 1
        return self

    def remove_listener(self, event: str, cb: Callable) -> None:
        lst = self._listeners.get(event)
        if not lst:
            return
        for i, fn in enumerate(lst):
            if fn is cb or getattr(fn, '__wrapped__', None) is cb:
                del lst[i]
                self._ver += 1
                break
        if not lst:
            self._listeners.pop(event, None)

    def remove_all_listeners(self, event: str | None = None) -> None:
        if event is None:
            self._listeners.clear()
        else:
            self._listeners.pop(event, None)
        self._ver += 1

    def listeners(self, event: str) -> list[Callable]:
        return list(self._listeners.get(event, ()))

    def listener_count(self, event: str) -> int:
        return len(self._listeners.get(event, ()))

    def emit(self, event: str, *args: Any) -> bool:
        """Dispatch synchronously.  A listener deregistered by an earlier
        listener in the same emit is skipped.  Returns True if anyone was
        listening."""
        snapshot = self._listeners.get(event)
        if not snapshot:
            return False
        if len(snapshot) == 1:
            # Hot path ('packet' and friends have one listener): no
            # snapshot copy, no membership scans.  Nothing can
            # deregister the listener before it runs — there is no
            # earlier listener in this emit to do so.
            snapshot[0](*args)
            return True
        # Multi-listener: liveness checks (O(n) each) are only needed
        # for callbacks dispatched AFTER the registry mutated — a
        # server db emitter carries 1 listener per subscribed
        # connection, and O(n^2) per event would melt at fleet scale.
        ver0 = self._ver
        for cb in list(snapshot):
            if self._ver != ver0:
                live = self._listeners.get(event)
                if live is None:
                    break
                if cb not in live:
                    continue
            cb(*args)
        return True


log = logging.getLogger('zkstream_tpu')
