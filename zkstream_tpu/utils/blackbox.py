"""The black-box plane: a crash-durable flight recorder per member.

Every other telemetry surface — the span ring (utils/trace.py), the
tick ledger and Collector (utils/metrics.py), the FSM census
(utils/fsm.py) — lives in process memory, so the one member whose
story matters most in a chaos post-mortem (the SIGKILL'd leader)
contributes nothing to the merged timeline.  This module fixes that:
each member appends schema-stamped **frames** to a bounded on-disk
ring in its WAL directory, CRC32C-framed exactly like the WAL itself
(server/persist.py: a torn *final* record is the normal crash
signature and is tolerated; a bit-flip anywhere fails the checksum
and nothing at or past it is trusted).

A frame snapshots, at a configurable cadence (``ZKSTREAM_BLACKBOX_MS``):

- the full ``mntr`` counter inventory (``ZKServer.monitor_stats``),
- the tick ledger's per-phase p99s,
- the FSM census (live state machines per (fsm, state)),
- the tail of the member's span ring,

plus one explicit ``final`` frame flushed on clean ``stop()`` and one
``slow_op`` frame per span that exceeded ``ZKSTREAM_SLOW_OP_MS``
(carrying the span's whole zxid-keyed causal chain — the real-ZK
warn-threshold log line, but with spans).  Writes ride the same
executor-thread pattern as the WAL's group fsync: the loop snapshots,
a worker thread writes — the hot path never waits on the device.

Recovery side: :func:`scan_box` / :func:`read_box` verify and decode
a ring (``python -m zkstream_tpu blackbox DIR``), and
:func:`harvest_spans` lifts dead members' trace tails back into
``merge_timelines``-ready rings — which is how both chaos tiers give
a SIGKILL'd member a voice in ``chaos --trace-out``.

On-disk ring: ``blackbox.<member>.log`` plus at most one rotated
``blackbox.<member>.log.old`` — disk is bounded at ~2x
``cap_bytes`` regardless of uptime.  The files are co-tenants of the
WAL directory by design: ``scan_dir``/``reset_dir`` match only the
``wal.``/``snap.`` prefixes, so the recorder's files survive a
follower's snapshot bootstrap and never confuse WAL recovery.
"""

from __future__ import annotations

import json
import os
import struct
import time

#: Version stamp inside every frame body; consumers key on it.
BLACKBOX_SCHEMA = 1

#: Version stamp on every ``zkstream_tpu top --out`` JSONL row (the
#: continuous fleet collector's time-series).
TOP_SCHEMA = 1

#: File magic, persist.py style: module, version, newline.
MAGIC_BLACKBOX = b'ZKSBBX1\n'

#: Record framing shared with the WAL: ``>I length | >I crc32c(body)``
#: then the JSON body.  Reusing the exact layout keeps the torn/
#: bit-flip semantics (and the test corpus discipline) identical.
_REC_HDR = struct.Struct('>II')

#: Sanity cap on one frame (a full mntr inventory + a 64-span tail is
#: a few tens of KiB; anything near this is corruption, not data).
MAX_FRAME = 8 * 1024 * 1024

#: How many trailing spans of the member ring ride in each frame.
TRACE_TAIL = 64

#: ``zookeeper_slow_op_ms`` histogram buckets (ms): the slow-op
#: threshold family — sub-threshold ops never observe here.
SLOW_OP_BUCKETS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                   1000.0, 2500.0, 5000.0, 10000.0)

METRIC_SLOW_OP_MS = 'zookeeper_slow_op_ms'


def blackbox_enabled() -> bool:
    """Process-wide default for the flight recorder.
    ``ZKSTREAM_NO_BLACKBOX=1`` disables it — the off arm of the
    paired overhead family (`bench.py --blackbox`), mirroring the
    WAL/trace/watchtable kill switches."""
    return os.environ.get('ZKSTREAM_NO_BLACKBOX') != '1'


def blackbox_interval_ms() -> float:
    """Frame cadence in ms (``ZKSTREAM_BLACKBOX_MS``, default 250):
    how much history one frame covers, and the most telemetry a crash
    can lose."""
    try:
        return float(os.environ.get('ZKSTREAM_BLACKBOX_MS', '250'))
    except ValueError:
        return 250.0


def slow_op_ms() -> float:
    """The slow-op digest threshold in ms (``ZKSTREAM_SLOW_OP_MS``,
    default 500): any span on an instrumented ring whose duration
    meets it gets its causal chain persisted and counted
    (``zk_slow_ops_total``).  Clean schedules at the default must
    count zero (tests/test_blackbox.py asserts it)."""
    try:
        return float(os.environ.get('ZKSTREAM_SLOW_OP_MS', '500'))
    except ValueError:
        return 500.0


def box_path(directory: str, member: str) -> str:
    return os.path.join(directory, 'blackbox.%s.log' % (member,))


def _crc32c(data: bytes) -> int:
    # the WAL's tiered impl (C extension when built, else the sliced
    # software Castagnoli) — one checksum algorithm per repo
    from ..server.persist import crc32c
    return crc32c(data)


def encode_frame(body: dict) -> bytes:
    """One CRC-framed record: length, crc32c(body), JSON body."""
    raw = json.dumps(body, separators=(',', ':'),
                     default=repr).encode('utf-8')
    return _REC_HDR.pack(len(raw), _crc32c(raw)) + raw


class BoxScan:
    """One ring file's verified contents.  ``status`` mirrors the WAL
    segment statuses: 'ok' | 'torn' (truncated tail — the crash
    signature, tolerated) | 'crc' (bit flip: rejected, nothing at or
    past it trusted) | 'corrupt' (bad magic / insane length /
    undecodable body)."""

    __slots__ = ('path', 'frames', 'status', 'error', 'valid_bytes',
                 'size')

    def __init__(self, path, frames, status, error, valid_bytes,
                 size):
        self.path = path
        self.frames = frames
        self.status = status
        self.error = error
        self.valid_bytes = valid_bytes
        self.size = size


def scan_box(path: str) -> BoxScan:
    """Verify + decode one ring file; replay stops at the first
    invalid record (the WAL's scan discipline — persist.py
    ``_scan_segment``)."""
    with open(path, 'rb') as f:
        buf = f.read()
    size = len(buf)
    if not buf.startswith(MAGIC_BLACKBOX):
        return BoxScan(path, [], 'corrupt', 'bad magic', 0, size)
    off = len(MAGIC_BLACKBOX)
    frames: list[dict] = []
    status, error = 'ok', None
    while off < size:
        if off + _REC_HDR.size > size:
            status, error = 'torn', 'truncated frame header'
            break
        ln, crc = _REC_HDR.unpack_from(buf, off)
        if not 0 < ln <= MAX_FRAME:
            status, error = 'corrupt', 'insane frame length %d' % ln
            break
        if off + _REC_HDR.size + ln > size:
            status, error = 'torn', 'truncated frame body'
            break
        body = buf[off + _REC_HDR.size:off + _REC_HDR.size + ln]
        if _crc32c(body) != crc:
            status, error = 'crc', ('frame %d fails CRC32C'
                                    % (len(frames),))
            break
        try:
            frames.append(json.loads(body.decode('utf-8')))
        except (ValueError, UnicodeDecodeError) as e:
            status, error = 'corrupt', ('frame %d undecodable: %s'
                                        % (len(frames), e))
            break
        off += _REC_HDR.size + ln
    return BoxScan(path, frames, status, error, off, size)


def list_boxes(directory: str) -> list[str]:
    """Member ids with a ring in ``directory`` (current files only;
    ``read_box`` folds each member's rotated half in itself)."""
    try:
        names = os.listdir(directory)
    except (FileNotFoundError, NotADirectoryError):
        return []
    out = []
    for name in sorted(names):
        if name.startswith('blackbox.') and name.endswith('.log'):
            out.append(name[len('blackbox.'):-len('.log')])
    return out


def read_box(directory: str, member: str) -> dict:
    """One member's full ring — the rotated ``.old`` half (always
    cleanly written: rotation happens between frames, never mid-one)
    followed by the current file, whose torn tail is tolerated.
    Returns ``{'member', 'frames', 'files': [BoxScan...], 'status'}``
    where ``status`` is the worst file status ('ok' < 'torn' <
    'crc' < 'corrupt')."""
    frames: list[dict] = []
    files: list[BoxScan] = []
    rank = {'ok': 0, 'torn': 1, 'crc': 2, 'corrupt': 3}
    status = 'ok'
    cur = box_path(directory, member)
    for path in (cur + '.old', cur):
        if not os.path.exists(path):
            continue
        scan = scan_box(path)
        files.append(scan)
        frames.extend(scan.frames)
        # a tear in the ROTATED half is not a crash signature (that
        # file was sealed by a live process): grade it corrupt
        st = scan.status
        if path.endswith('.old') and st == 'torn':
            st = 'corrupt'
        if rank[st] > rank[status]:
            status = st
    return {'member': member, 'frames': frames, 'files': files,
            'status': status}


def harvest_spans(directory: str) -> dict[str, list[dict]]:
    """Lift every member ring found in ``directory`` back into
    ``merge_timelines``-ready form: ``{'member:<id>': [span dicts]}``.

    Consecutive frames snapshot overlapping ring tails, so spans are
    deduplicated by (span id, op, wall time); slow-op frames
    contribute their persisted causal chains too.  Unreadable or
    corrupt rings contribute what their valid prefix holds — the
    whole point is salvaging a dead member's last words."""
    out: dict[str, list[dict]] = {}
    for member in list_boxes(directory):
        box = read_box(directory, member)
        seen: set = set()
        spans: list[dict] = []
        for frame in box['frames']:
            for span in (frame.get('trace_tail') or []) \
                    + (frame.get('chain') or []):
                key = (span.get('span'), span.get('op'),
                       span.get('t_wall'))
                if key in seen:
                    continue
                seen.add(key)
                spans.append(span)
        if spans:
            out['member:%s' % (member,)] = spans
    return out


class BlackBoxRecorder:
    """The per-member flight recorder: builds frames on the loop,
    writes them on an executor thread (the WAL group-fsync pattern —
    one write in flight, later frames queue behind it), rotates at
    ``cap_bytes`` so disk stays bounded, and flushes one final frame
    synchronously on clean stop.

    ``server`` supplies the snapshots (``monitor_stats``, ``ledger``,
    ``trace``); ``collector`` (optional) supplies the FSM registry
    and receives the ``zookeeper_slow_op_ms`` histogram."""

    def __init__(self, directory: str, member: str = '0',
                 server=None, interval_ms: float | None = None,
                 cap_bytes: int = 4 * 1024 * 1024,
                 collector=None):
        self.dir = directory
        self.member = member
        self.server = server
        self.interval_ms = (blackbox_interval_ms()
                            if interval_ms is None else interval_ms)
        self.cap_bytes = cap_bytes
        self.path = box_path(directory, member)
        #: frames appended + bytes written since construction (the
        #: ``zk_blackbox_frames`` / ``zk_blackbox_bytes`` mntr rows)
        self.frames = 0
        self.bytes_written = 0
        #: spans that crossed the slow-op threshold (the
        #: ``zk_slow_ops_total`` mntr row)
        self.slow_ops = 0
        self._seq = 0
        self._file = None
        self._file_bytes = 0
        self._loop = None
        self._handle = None
        self._inflight = False
        self._pending: list[bytes] = []
        self._closed = False
        self._hist = None
        if collector is not None:
            try:
                self._hist = collector.histogram(
                    METRIC_SLOW_OP_MS,
                    'Duration of ops/txn stages that crossed the '
                    'slow-op threshold (sub-threshold ops never '
                    'observe here)', buckets=SLOW_OP_BUCKETS)
            except ValueError:
                pass                  # shared collector, already bound

    # -- file plumbing ------------------------------------------------

    def _ensure_file(self) -> None:
        if self._file is not None:
            return
        os.makedirs(self.dir, exist_ok=True)
        self._file = open(self.path, 'ab')
        if self._file.tell() == 0:
            self._file.write(MAGIC_BLACKBOX)
            self._file.flush()
        self._file_bytes = self._file.tell()

    def _maybe_rotate(self) -> None:
        """Flip the ring: the current file becomes ``.old`` (replacing
        any previous one) and a fresh file starts — between frames
        only, and never while an executor write is in flight."""
        if self._file_bytes < self.cap_bytes or self._inflight:
            return
        self._file.close()
        self._file = None
        os.replace(self.path, self.path + '.old')
        self._ensure_file()

    def _write_sync(self, blob: bytes) -> None:
        """Blocking write + fsync — executor threads and the
        (sync) stop path only; never the loop."""
        self._ensure_file()
        self._file.write(blob)
        self._file.flush()
        os.fsync(self._file.fileno())
        self._file_bytes += len(blob)
        self.bytes_written += len(blob)

    def _dispatch(self) -> None:
        """Ship the queued frames to the executor (one write in
        flight at a time, like the WAL's group sync)."""
        if self._inflight or self._closed or not self._pending:
            return
        blob = b''.join(self._pending)
        self._pending.clear()
        self._inflight = True

        def done(fut) -> None:
            self._inflight = False
            try:
                fut.result()
            except OSError:
                pass                  # telemetry: never take the
                # member down over its own black box
            if not self._closed:
                self._maybe_rotate()
                self._dispatch()      # frames queued meanwhile

        self._loop.run_in_executor(
            None, self._write_sync, blob).add_done_callback(done)

    def _append(self, body: dict) -> None:
        rec = encode_frame(body)
        self.frames += 1
        self._seq += 1
        if self._loop is not None and not self._closed:
            self._pending.append(rec)
            self._dispatch()
        else:
            # no loop (offline/unit use, or the stop path): inline
            self._write_sync(rec)
            self._maybe_rotate()

    # -- frame content ------------------------------------------------

    def _snapshot(self, kind: str) -> dict:
        srv = self.server
        body: dict = {
            'blackbox_schema': BLACKBOX_SCHEMA,
            'kind': kind,
            'member': self.member,
            'seq': self._seq,
            't_wall': round(time.time(), 6),
        }
        if srv is None:
            return body
        try:
            body['mntr'] = {k: v for k, v in srv.monitor_stats()}
        except Exception as e:        # a half-torn-down server must
            body['mntr_error'] = repr(e)   # not lose the frame
        ledger = getattr(srv, 'ledger', None)
        if ledger is not None:
            phases = {}
            for phase in type(ledger).PHASES:
                p99 = ledger.phase_p99(phase)
                if p99 is not None:
                    phases[phase] = round(p99, 4)
            body['phases'] = phases
            body['ticks'] = ledger.ticks
        collector = getattr(srv, 'collector', None)
        registry = getattr(collector, '_fsm_registry', None)
        if registry is not None:
            from .fsm import _fsm_state_counts
            body['fsm'] = {
                ','.join('%s=%s' % kv for kv in key): n
                for key, n in _fsm_state_counts(registry).items()}
        trace = getattr(srv, 'trace', None)
        if trace is not None:
            body['trace_dropped'] = trace.dropped
            body['trace_tail'] = trace.dump()[-TRACE_TAIL:]
        return body

    # -- public surface -----------------------------------------------

    def start(self, loop) -> None:
        """Arm the cadence on ``loop``; idempotent (restart re-arms
        a recorder its server's stop() closed)."""
        self._loop = loop
        self._closed = False
        self._ensure_file()
        if self._handle is None:
            self._schedule()

    def _schedule(self) -> None:
        self._handle = self._loop.call_later(
            self.interval_ms / 1000.0, self._tick)

    def _tick(self) -> None:
        self._handle = None
        if self._closed:
            return
        self._append(self._snapshot('periodic'))
        self._schedule()

    def capture(self, kind: str = 'periodic') -> None:
        """Record one frame now (out of cadence)."""
        self._append(self._snapshot(kind))

    def slow_span(self, span) -> None:
        """The span ring's slow-op hook (utils/trace.py
        ``TraceRing.on_slow``): persist the offending span's whole
        zxid-keyed causal chain as a ``slow_op`` frame and count it.
        Counting is loop-side; the write rides the executor queue."""
        self.slow_ops += 1
        if self._hist is not None and span.duration_ms is not None:
            self._hist.observe(span.duration_ms)
        body = self._snapshot('slow_op')
        body['slow'] = span.to_dict()
        trace = getattr(self.server, 'trace', None)
        if trace is not None and span.zxid is not None:
            body['chain'] = [s.to_dict() for s in trace.spans()
                             if s.zxid == span.zxid]
        else:
            body['chain'] = [span.to_dict()]
        self._append(body)

    def stop(self, final: bool = True) -> None:
        """Disarm the cadence, drain queued frames, flush one final
        frame synchronously (fsynced — the very thing a post-mortem
        reads first), and close the file.  Clean-stop only; a SIGKILL
        leaves whatever the executor had durably written, torn tail
        included — which scan_box tolerates by design."""
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        if self._closed:
            return
        self._closed = True
        blob = b''.join(self._pending)
        self._pending.clear()
        if final:
            blob += encode_frame(self._snapshot('final'))
            self.frames += 1
            self._seq += 1
        if blob:
            try:
                self._write_sync(blob)
            except OSError:
                pass
        if self._file is not None:
            self._file.close()
            self._file = None
