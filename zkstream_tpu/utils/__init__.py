"""Cross-cutting infrastructure: event emitter, Moore-machine FSM base,
metrics (the rebuild's equivalents of the reference's mooremachine /
events / artedi dependencies)."""

from .events import EventEmitter  # noqa: F401
from .fsm import FSM, StateScope  # noqa: F401
from .metrics import Collector, Counter, Gauge, Histogram  # noqa: F401
from .trace import Span, TraceRing  # noqa: F401
