"""Prometheus-style metrics: counters, gauges, and histograms.

The reference records client events through the artedi collector
(reference: lib/client.js:46-61, lib/zk-session.js:61-65).  This is a
dependency-free equivalent: labelled counters, pull-model gauges, and
cumulative-bucket histograms with text exposition in the Prometheus
format.  A caller may supply their own ``Collector`` to ``Client`` or
let one be created internally, as in the reference.

Label values are escaped per the Prometheus exposition spec
(backslash, double quote, and newline), so a path or error string can
ride in a label without producing unparseable scrape output.
"""

from __future__ import annotations

import time

from .aio import ambient_loop


def escape_label_value(value) -> str:
    """Escape a label value per the Prometheus text exposition format:
    ``\\`` -> ``\\\\``, ``"`` -> ``\\"``, newline -> ``\\n``."""
    return (str(value)
            .replace('\\', '\\\\')
            .replace('"', '\\"')
            .replace('\n', '\\n'))


def _render_labels(key: tuple[tuple[str, str], ...],
                   extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = tuple(key) + tuple(extra)
    if not pairs:
        return ''
    return '{%s}' % ','.join(
        '%s="%s"' % (k, escape_label_value(v)) for k, v in pairs)


def _label_key(labels) -> tuple[tuple[str, str], ...]:
    """Normalize a label set (dict, or an iterable of (k, v) pairs —
    MultiGauge callbacks need hashable keys) to a sorted tuple."""
    if not labels:
        return ()
    items = labels.items() if isinstance(labels, dict) else labels
    return tuple(sorted(items))


class Counter:
    def __init__(self, name: str, help_text: str = ''):
        self.name = name
        self.help = help_text
        self._values: dict[tuple[tuple[str, str], ...], float] = {}

    def increment(self, labels: dict[str, str] | None = None,
                  by: float = 1.0) -> None:
        key = _label_key(labels)
        self._values[key] = self._values.get(key, 0.0) + by

    def value(self, labels: dict[str, str] | None = None) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def label_keys(self) -> list[tuple[tuple[str, str], ...]]:
        """Every label set with a recorded value (scrape helpers walk
        this to enumerate series, like Histogram.label_keys)."""
        return list(self._values.keys())

    def expose(self) -> str:
        lines = []
        if self.help:
            lines.append('# HELP %s %s' % (self.name, self.help))
        lines.append('# TYPE %s counter' % (self.name,))
        for key, val in sorted(self._values.items()):
            lines.append('%s%s %s' % (self.name, _render_labels(key),
                                      val))
        return '\n'.join(lines)


class Gauge:
    """A pull-model gauge: the value is read from a callback at
    exposition time — zero hot-path cost for instrumented components
    (the fleet ingest binds its tick/frame counters this way)."""

    def __init__(self, name: str, fn, help_text: str = ''):
        self.name = name
        self.help = help_text
        self._fn = fn

    def expose(self) -> str:
        lines = []
        if self.help:
            lines.append('# HELP %s %s' % (self.name, self.help))
        lines.append('# TYPE %s gauge' % (self.name,))
        try:
            val = self._fn()
        except Exception:  # a dead callback must not sink exposition
            val = float('nan')
        lines.append('%s %s' % (self.name, val))
        return '\n'.join(lines)


class MultiGauge:
    """A pull-model gauge with one series per label set: the callback
    returns ``{labels_dict: value}`` at exposition time.  Used for the
    FSM current-state gauge, where the series population (which
    machines exist, which states they sit in) changes at runtime."""

    def __init__(self, name: str, fn, help_text: str = ''):
        self.name = name
        self.help = help_text
        self._fn = fn

    def expose(self) -> str:
        lines = []
        if self.help:
            lines.append('# HELP %s %s' % (self.name, self.help))
        lines.append('# TYPE %s gauge' % (self.name,))
        try:
            values = {_label_key(labels): val
                      for labels, val in self._fn().items()}
        except Exception:  # a dead callback must not sink exposition
            lines.append('%s %s' % (self.name, float('nan')))
            return '\n'.join(lines)
        for key, val in sorted(values.items()):
            lines.append('%s%s %s' % (self.name, _render_labels(key),
                                      val))
        return '\n'.join(lines)


#: Default latency buckets, milliseconds: sub-ms client-loop hops up
#: through multi-second retry storms.
DEFAULT_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                   250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)


class Histogram:
    """A labelled Prometheus histogram: cumulative ``_bucket`` series
    (``le`` upper bounds plus ``+Inf``), ``_sum``, and ``_count``.

    ``observe`` is the hot-path call: one bisect-free linear scan over
    a small tuple of bounds plus two adds — cheap enough for per-op
    recording."""

    def __init__(self, name: str, help_text: str = '',
                 buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help_text
        bounds = tuple(sorted(float(b) for b in buckets))
        assert bounds, 'histogram needs at least one bucket bound'
        self.buckets = bounds
        #: label key -> [per-bucket counts..., +Inf count, sum]
        self._series: dict[tuple[tuple[str, str], ...], list] = {}

    def _row(self, labels: dict[str, str] | None) -> list:
        key = _label_key(labels)
        row = self._series.get(key)
        if row is None:
            row = self._series[key] = [0] * (len(self.buckets) + 1) \
                + [0.0]
        return row

    def observe(self, value: float,
                labels: dict[str, str] | None = None) -> None:
        row = self._row(labels)
        for i, bound in enumerate(self.buckets):
            if value <= bound:
                row[i] += 1
                break
        else:
            row[len(self.buckets)] += 1     # +Inf-only
        row[-1] += value

    def count(self, labels: dict[str, str] | None = None) -> int:
        row = self._series.get(_label_key(labels))
        return sum(row[:-1]) if row is not None else 0

    def sum(self, labels: dict[str, str] | None = None) -> float:
        row = self._series.get(_label_key(labels))
        return row[-1] if row is not None else 0.0

    def label_keys(self) -> list[tuple[tuple[str, str], ...]]:
        """Every label set this histogram holds series for (sorted
        key tuples, as ``_label_key`` produces)."""
        return list(self._series)

    def percentile(self, q: float,
                   labels: dict[str, str] | None = None) -> float:
        """Estimate the ``q``-th percentile (0..100) the way
        ``histogram_quantile`` does: find the bucket the rank falls
        in, interpolate linearly inside it.  The +Inf bucket clamps
        to the largest finite bound (no upper edge to interpolate
        toward); an empty series returns NaN."""
        row = self._series.get(_label_key(labels))
        if row is None:
            return float('nan')
        total = sum(row[:-1])
        if total == 0:
            return float('nan')
        rank = q / 100.0 * total
        cum = 0.0
        lo = 0.0
        for i, bound in enumerate(self.buckets):
            prev = cum
            cum += row[i]
            if cum >= rank:
                frac = (rank - prev) / row[i] if row[i] else 0.0
                return lo + (bound - lo) * frac
            lo = bound
        return self.buckets[-1]

    def bucket_value(self, le: float,
                     labels: dict[str, str] | None = None) -> int:
        """Cumulative count for the bucket with upper bound ``le``
        (``float('inf')`` for the +Inf bucket)."""
        row = self._series.get(_label_key(labels))
        if row is None:
            return 0
        if le == float('inf'):
            return sum(row[:-1])
        idx = self.buckets.index(float(le))
        return sum(row[:idx + 1])

    @staticmethod
    def _fmt_bound(bound: float) -> str:
        return '%g' % (bound,)

    def expose(self) -> str:
        lines = []
        if self.help:
            lines.append('# HELP %s %s' % (self.name, self.help))
        lines.append('# TYPE %s histogram' % (self.name,))
        for key, row in sorted(self._series.items()):
            cum = 0
            for i, bound in enumerate(self.buckets):
                cum += row[i]
                lines.append('%s_bucket%s %d' % (
                    self.name,
                    _render_labels(key, (('le', self._fmt_bound(bound)),)),
                    cum))
            cum += row[len(self.buckets)]
            lines.append('%s_bucket%s %d' % (
                self.name, _render_labels(key, (('le', '+Inf'),)), cum))
            lines.append('%s_sum%s %s' % (self.name,
                                          _render_labels(key), row[-1]))
            lines.append('%s_count%s %d' % (self.name,
                                            _render_labels(key), cum))
        return '\n'.join(lines)


METRIC_TICK = 'zk_tick_ms'
METRIC_TICK_PHASE = 'zk_tick_phase_ms'

#: Tick/phase duration buckets, ms: a busy tick on this stack spans
#: tens of microseconds (one pipelined reply) up to tens of
#: milliseconds (a wide fan-out flush or a slow-device fsync).
TICK_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                10.0, 25.0, 50.0)


class TickLedger:
    """Per-busy-tick phase accounting for one server member.

    The busy loop tick is the unit every plane already coalesces on —
    one cork flush, one group fsync, one fan-out flush per tick — but
    nothing said where the tick's wall time went.  The ledger splits
    it: call sites bracket their work with :meth:`enter`/:meth:`exit`
    (nested sections subtract cleanly, so a cork flush inside a shard
    flush is counted once), and when the burst goes quiet the tick
    closes — per-phase durations land in ``zk_tick_phase_ms{phase=}``
    and the burst's wall span in ``zk_tick_ms``.

    Phases (server/server.py wires them):

    - ``rx_drain`` — the ingress plane's batched receive: kernel-to-
      user time for one shard's dirty set (io/ingress.py; ~0 on the
      single-loop validator, whose reads are awaited, not drained);
    - ``decode_apply`` — request decode + handler dispatch (store
      apply and WAL append included, minus nested phases);
    - ``fsync_gate`` — loop-blocking durability-barrier time (the
      inline fast-device fsync, ``sync='always'`` appends, the
      synchronous barrier on close paths);
    - ``cork_flush`` — send-plane buffer join + transport write;
    - ``fanout_flush`` — the watch table's per-shard flush loop
      (minus the nested cork writes it triggers).

    A "tick" here is the whole burst: asyncio runs ``call_soon``
    callbacks scheduled during a callback in the *next* loop
    iteration, so the cork/fan-out flushes of one logical tick land
    one iteration after the decode that corked them — the close
    callback re-arms while activity continues and finalizes on the
    first quiet iteration.  Phase sums are <= the tick wall span by
    construction; the gap is un-instrumented loop work.

    Works without a collector (mntr-only servers keep their own
    histograms); with one, the same histograms are registered for
    scraping (``scrape_tick_cells`` summarizes them per bench cell).
    """

    PHASES = ('rx_drain', 'decode_apply', 'fsync_gate', 'cork_flush',
              'fanout_flush')

    #: Close a still-active burst after this many loop iterations
    #: anyway: under saturating back-to-back load every iteration has
    #: new phase activity and a pure quiet-pass rule would never
    #: close — the ledger then reports bounded burst slices (shares
    #: stay exact; only the per-tick bucketing coarsens).
    MAX_DEFERS = 8

    __slots__ = ('ticks', 'phase_hist', 'tick_hist', 'last_tick',
                 '_acc', '_stack', '_first', '_last', '_scheduled',
                 '_gen', '_sched_gen', '_defers')

    def __init__(self, collector=None):
        self.ticks = 0
        self.last_tick: dict | None = None
        self._acc: dict[str, float] = {}
        self._stack: list = []      # [phase, t0, child_seconds]
        self._first = 0.0
        self._last = 0.0
        self._scheduled = False
        self._gen = 0
        self._sched_gen = -1
        self._defers = 0
        source = collector if collector is not None else Collector()
        self.phase_hist = source.histogram(
            METRIC_TICK_PHASE,
            'Busy-tick time by phase, ms (rx_drain | decode_apply | '
            'fsync_gate | cork_flush | fanout_flush)',
            buckets=TICK_BUCKETS)
        self.tick_hist = source.histogram(
            METRIC_TICK, 'Busy-tick wall span, ms',
            buckets=TICK_BUCKETS)

    def enter(self, phase: str) -> None:
        """Open one phase section (re-entrant across phases: a nested
        section's time is subtracted from its parent)."""
        now = time.perf_counter()
        if not self._stack and not self._acc:
            self._first = now
        self._gen += 1
        self._stack.append([phase, now, 0.0])
        if not self._scheduled:
            # -1 forces the close callback to re-arm at least once:
            # it is queued BEFORE the tick's own spill-over callbacks
            # (cork/fan-out flushes land behind it in the same
            # iteration), so closing on the first run would split one
            # logical tick in two
            self._sched_gen = -1
            try:
                ambient_loop().call_soon(self._tick_close)
            except RuntimeError:
                return          # no loop (unit test): close manually
            self._scheduled = True

    def exit(self) -> None:
        """Close the innermost open section."""
        now = time.perf_counter()
        phase, t0, child = self._stack.pop()
        dur = now - t0
        self._acc[phase] = self._acc.get(phase, 0.0) + dur - child
        if self._stack:
            self._stack[-1][2] += dur
        self._last = now

    def _tick_close(self) -> None:
        self._scheduled = False
        self._defers += 1
        if self._stack or (self._gen != self._sched_gen
                           and self._defers < self.MAX_DEFERS):
            # activity since the last look (the burst spilled into
            # this iteration — cork/fan-out callbacks of the same
            # logical tick): look again next iteration; close after
            # one fully quiet pass, or at MAX_DEFERS under
            # saturating load
            self._sched_gen = self._gen
            try:
                ambient_loop().call_soon(self._tick_close)
            except RuntimeError:
                return
            self._scheduled = True
            return
        self.close_tick()

    def close_tick(self) -> None:
        """Finalize the current tick: observe every accumulated phase
        and the tick wall span.  Loop-driven normally; callable
        directly where no loop runs (unit tests)."""
        if not self._acc or self._stack:
            return
        self._defers = 0
        total_ms = (self._last - self._first) * 1000.0
        phases = {p: round(s * 1000.0, 6)
                  for p, s in self._acc.items()}
        self._acc = {}
        self.ticks += 1
        for phase, ms in phases.items():
            self.phase_hist.observe(ms, {'phase': phase})
        self.tick_hist.observe(total_ms)
        self.last_tick = {'total_ms': round(total_ms, 6),
                          'phases': phases}

    def phase_p99(self, phase: str) -> float | None:
        """p99 of one phase's per-tick duration, ms (None when the
        phase never ran) — the mntr ``zk_tick_phase_ms_p99`` rows."""
        labels = {'phase': phase}
        if not self.phase_hist.count(labels):
            return None
        return self.phase_hist.percentile(99, labels)


def scrape_tick_cells(collector) -> dict:
    """Summarize the tick ledger for bench cells (bench.py write-heavy
    and fan-out families): tick count + wall-span p50/p99, and per
    phase the per-tick p50/p99 plus ``share`` — the fraction of
    ledgered tick time the phase ate, the number the accept-shard and
    io_uring roadmap items are gated on."""
    out: dict = {}
    try:
        th = collector.get_collector(METRIC_TICK)
        ph = collector.get_collector(METRIC_TICK_PHASE)
    except ValueError:
        return out
    n = th.count()
    if not n:
        return out
    out['ticks'] = n
    out['tick_ms_p50'] = round(th.percentile(50), 4)
    out['tick_ms_p99'] = round(th.percentile(99), 4)
    total = th.sum()
    phases: dict = {}
    for key in ph.label_keys():
        labels = dict(key)
        name = labels.get('phase', '')
        c = ph.count(labels)
        if not c:
            continue
        phases[name] = {
            'count': c,
            'ms_p50': round(ph.percentile(50, labels), 4),
            'ms_p99': round(ph.percentile(99, labels), 4),
            'share': round(ph.sum(labels) / total, 3) if total else 0.0,
        }
    if phases:
        out['phases'] = phases
    return out


def sign_test_p(wins: int, losses: int) -> float:
    """Two-sided exact sign test (ties dropped): the probability of a
    split at least this lopsided under H0 = deltas symmetric around 0.
    Shared by every paired A/B study (tools/sweep_crossover.py's cork
    pairs, bench.py --wal's durability arms) so the published p-value
    tables can never drift apart."""
    import math

    n = wins + losses
    if n == 0:
        return 1.0
    k = min(wins, losses)
    p = 2.0 * sum(math.comb(n, i) for i in range(k + 1)) / (2.0 ** n)
    return min(1.0, p)


class Collector:
    def __init__(self) -> None:
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge | MultiGauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def _check_collision(self, name: str, kind: str) -> None:
        for other_kind, table in (('counter', self._counters),
                                  ('gauge', self._gauges),
                                  ('histogram', self._histograms)):
            if kind != other_kind and name in table:
                raise ValueError(
                    'metric %r already registered as a %s'
                    % (name, other_kind))

    def counter(self, name: str, help_text: str = '') -> Counter:
        """Create (or fetch) a counter by name — idempotent, like
        artedi's collector.counter()."""
        self._check_collision(name, 'counter')
        if name not in self._counters:
            self._counters[name] = Counter(name, help_text)
        return self._counters[name]

    def histogram(self, name: str, help_text: str = '',
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        """Create (or fetch) a histogram by name — idempotent like
        :meth:`counter`, so shared collectors (many clients, one
        scrape) register per-op latency once.  Re-registering with
        DIFFERENT bucket bounds raises: silently handing back the
        first registrant's buckets would mis-bucket the second
        registrant's observations with no warning."""
        self._check_collision(name, 'histogram')
        existing = self._histograms.get(name)
        if existing is not None:
            want = tuple(sorted(float(b) for b in buckets))
            if want != existing.buckets:
                raise ValueError(
                    'histogram %r already registered with buckets %r '
                    '(requested %r); use a distinct name/prefix'
                    % (name, existing.buckets, want))
            return existing
        self._histograms[name] = Histogram(name, help_text, buckets)
        return self._histograms[name]

    def _check_gauge_free(self, name: str) -> None:
        """Gauges are never idempotent — a same-name registration (of
        any kind) raises: silently replacing would drop the first
        registrant's series (bind two instrumented components under
        distinct prefixes instead)."""
        self._check_collision(name, 'gauge')
        if name in self._gauges:
            raise ValueError(
                'metric %r already registered; use a distinct '
                'name/prefix' % (name,))

    def gauge(self, name: str, fn, help_text: str = '') -> Gauge:
        """Register a callback-backed gauge (see
        :meth:`_check_gauge_free` for the collision policy)."""
        self._check_gauge_free(name)
        self._gauges[name] = Gauge(name, fn, help_text)
        return self._gauges[name]

    def multi_gauge(self, name: str, fn,
                    help_text: str = '') -> MultiGauge:
        """Register a labelled pull gauge (callback returns
        ``{labels: value}``); same collision policy as :meth:`gauge`."""
        self._check_gauge_free(name)
        self._gauges[name] = MultiGauge(name, fn, help_text)
        return self._gauges[name]

    def histograms(self) -> list[Histogram]:
        return list(self._histograms.values())

    def get_collector(self, name: str):
        if name in self._counters:
            return self._counters[name]
        if name in self._histograms:
            return self._histograms[name]
        if name in self._gauges:
            return self._gauges[name]
        registered = sorted(list(self._counters) + list(self._gauges)
                            + list(self._histograms))
        raise ValueError(
            'no metric %r registered; registered names: %s'
            % (name, ', '.join(registered) or '(none)'))

    def expose(self) -> str:
        parts = [c.expose() for c in self._counters.values()]
        parts += [h.expose() for h in self._histograms.values()]
        parts += [g.expose() for g in self._gauges.values()]
        return '\n'.join(parts)
