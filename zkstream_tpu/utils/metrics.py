"""Prometheus-style metrics counters.

The reference records client events through the artedi collector
(reference: lib/client.js:46-61, lib/zk-session.js:61-65).  This is a
dependency-free equivalent: labelled counters plus text exposition in
the Prometheus format.  A caller may supply their own ``Collector`` to
``Client`` or let one be created internally, as in the reference.
"""

from __future__ import annotations


class Counter:
    def __init__(self, name: str, help_text: str = ''):
        self.name = name
        self.help = help_text
        self._values: dict[tuple[tuple[str, str], ...], float] = {}

    def increment(self, labels: dict[str, str] | None = None,
                  by: float = 1.0) -> None:
        key = tuple(sorted((labels or {}).items()))
        self._values[key] = self._values.get(key, 0.0) + by

    def value(self, labels: dict[str, str] | None = None) -> float:
        return self._values.get(tuple(sorted((labels or {}).items())), 0.0)

    def expose(self) -> str:
        lines = []
        if self.help:
            lines.append('# HELP %s %s' % (self.name, self.help))
        lines.append('# TYPE %s counter' % (self.name,))
        for key, val in sorted(self._values.items()):
            if key:
                labelstr = '{%s}' % ','.join(
                    '%s="%s"' % (k, v) for k, v in key)
            else:
                labelstr = ''
            lines.append('%s%s %s' % (self.name, labelstr, val))
        return '\n'.join(lines)


class Gauge:
    """A pull-model gauge: the value is read from a callback at
    exposition time — zero hot-path cost for instrumented components
    (the fleet ingest binds its tick/frame counters this way)."""

    def __init__(self, name: str, fn, help_text: str = ''):
        self.name = name
        self.help = help_text
        self._fn = fn

    def expose(self) -> str:
        lines = []
        if self.help:
            lines.append('# HELP %s %s' % (self.name, self.help))
        lines.append('# TYPE %s gauge' % (self.name,))
        try:
            val = self._fn()
        except Exception:  # a dead callback must not sink exposition
            val = float('nan')
        lines.append('%s %s' % (self.name, val))
        return '\n'.join(lines)


class Collector:
    def __init__(self) -> None:
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}

    def counter(self, name: str, help_text: str = '') -> Counter:
        """Create (or fetch) a counter by name — idempotent, like
        artedi's collector.counter()."""
        if name in self._gauges:
            raise ValueError(
                'metric %r already registered as a gauge' % (name,))
        if name not in self._counters:
            self._counters[name] = Counter(name, help_text)
        return self._counters[name]

    def gauge(self, name: str, fn, help_text: str = '') -> Gauge:
        """Register a callback-backed gauge.  A name collision raises:
        silently replacing would drop the first registrant's series
        (bind two instrumented components under distinct prefixes
        instead)."""
        if name in self._gauges or name in self._counters:
            raise ValueError(
                'metric %r already registered; use a distinct '
                'name/prefix' % (name,))
        self._gauges[name] = Gauge(name, fn, help_text)
        return self._gauges[name]

    def get_collector(self, name: str):
        if name in self._counters:
            return self._counters[name]
        return self._gauges[name]

    def expose(self) -> str:
        parts = [c.expose() for c in self._counters.values()]
        parts += [g.expose() for g in self._gauges.values()]
        return '\n'.join(parts)
