"""Host-platform control for tests and multi-chip dry runs.

The deployment image boots every interpreter with a remote-TPU PJRT
plugin pre-registered (a site hook driven by ``PALLAS_AXON_POOL_IPS``)
and pins ``jax_platforms`` to prefer it.  Unit tests and the virtual
multi-chip dry run must instead run on N in-process CPU devices —
touching the remote chip from dozens of tests would be slow at best.
``force_cpu`` re-points JAX at the CPU backend even after the hook has
run: it must be called before the first backend initialization (first
``jax.devices()`` / first traced op) in the process.
"""

from __future__ import annotations

import os


def bounded_probe(code: str, budget_s: float) -> tuple[str, str]:
    """Run ``python -c code`` in a fresh subprocess with a hard
    budget; returns ``(status, detail)`` where status is ``'ok'``
    (exit 0), ``'error'`` (nonzero exit; detail carries the last
    stderr line), or ``'timeout'`` (killed by process group after the
    budget).

    This is the one safe way to ask a possibly-wedged tunneled
    accelerator anything: the child owns its own session so the whole
    group dies on timeout, and no pipes are held that its tunnel
    helpers could inherit and wedge the parent draining (stderr goes
    to a temp file, never a pipe).  Shared by bench._guard_backend
    and tools/tpu_window.py.
    """
    import signal
    import subprocess
    import sys
    import tempfile

    with tempfile.TemporaryFile() as errf:
        proc = subprocess.Popen(
            [sys.executable, '-c', code],
            stdout=subprocess.DEVNULL, stderr=errf,
            start_new_session=True)
        try:
            rc = proc.wait(timeout=budget_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()
            return 'timeout', ''
        if rc == 0:
            return 'ok', ''
        errf.seek(0)
        tail = errf.read().decode(errors='replace').strip()
        return 'error', (tail.splitlines()[-1:] or ['?'])[0]


def force_cpu(n_devices: int | None = None) -> None:
    """Pin this process's JAX to the host CPU platform.

    With ``n_devices``, also request that many virtual CPU devices
    (``--xla_force_host_platform_device_count``) — only effective if
    the CPU backend has not been initialized yet.
    """
    if n_devices is not None:
        flags = os.environ.get('XLA_FLAGS', '')
        flag = f'--xla_force_host_platform_device_count={n_devices}'
        if '--xla_force_host_platform_device_count' in flags:
            flags = ' '.join(
                flag if f.startswith('--xla_force_host_platform_device_count')
                else f for f in flags.split())
        else:
            flags = (flags + ' ' + flag).strip()
        os.environ['XLA_FLAGS'] = flags
    os.environ['JAX_PLATFORMS'] = 'cpu'

    import jax

    jax.config.update('jax_platforms', 'cpu')
    try:  # drop the remote plugin's factory so backend discovery
        # cannot stall dialing a TPU the tests must not touch
        from jax._src import xla_bridge as xb

        xb._backend_factories.pop('axon', None)
    except Exception:
        pass
