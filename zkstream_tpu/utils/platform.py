"""Host-platform control for tests and multi-chip dry runs.

The deployment image boots every interpreter with a remote-TPU PJRT
plugin pre-registered (a site hook driven by ``PALLAS_AXON_POOL_IPS``)
and pins ``jax_platforms`` to prefer it.  Unit tests and the virtual
multi-chip dry run must instead run on N in-process CPU devices —
touching the remote chip from dozens of tests would be slow at best.
``force_cpu`` re-points JAX at the CPU backend even after the hook has
run: it must be called before the first backend initialization (first
``jax.devices()`` / first traced op) in the process.
"""

from __future__ import annotations

import os


def force_cpu(n_devices: int | None = None) -> None:
    """Pin this process's JAX to the host CPU platform.

    With ``n_devices``, also request that many virtual CPU devices
    (``--xla_force_host_platform_device_count``) — only effective if
    the CPU backend has not been initialized yet.
    """
    if n_devices is not None:
        flags = os.environ.get('XLA_FLAGS', '')
        flag = f'--xla_force_host_platform_device_count={n_devices}'
        if '--xla_force_host_platform_device_count' in flags:
            flags = ' '.join(
                flag if f.startswith('--xla_force_host_platform_device_count')
                else f for f in flags.split())
        else:
            flags = (flags + ' ' + flag).strip()
        os.environ['XLA_FLAGS'] = flags
    os.environ['JAX_PLATFORMS'] = 'cpu'

    import jax

    jax.config.update('jax_platforms', 'cpu')
    try:  # drop the remote plugin's factory so backend discovery
        # cannot stall dialing a TPU the tests must not touch
        from jax._src import xla_bridge as xb

        xb._backend_factories.pop('axon', None)
    except Exception:
        pass
