"""Host-platform control for tests and multi-chip dry runs.

The deployment image boots every interpreter with a remote-TPU PJRT
plugin pre-registered (a site hook driven by ``PALLAS_AXON_POOL_IPS``)
and pins ``jax_platforms`` to prefer it.  Unit tests and the virtual
multi-chip dry run must instead run on N in-process CPU devices —
touching the remote chip from dozens of tests would be slow at best.
``force_cpu`` re-points JAX at the CPU backend even after the hook has
run: it must be called before the first backend initialization (first
``jax.devices()`` / first traced op) in the process.
"""

from __future__ import annotations

import os


def bounded_run(argv: list[str], budget_s: float,
                capture_stderr: bool = False,
                env: dict | None = None) -> tuple[str, str, int]:
    """Run ``argv`` in its own process group with a hard budget;
    returns ``(status, detail, rc)`` where status is ``'ok'``
    (exit 0), ``'error'`` (nonzero exit), ``'killed'`` (the child
    died on a signal — rc < 0 — which on a flaky accelerator tunnel
    is an environmental event like a timeout, not a deterministic
    program error), or ``'timeout'`` (whole group SIGKILLed after
    the budget; rc is -1).  With
    ``capture_stderr``, stdout is discarded and detail carries the
    child's last stderr line on error — via a temp file, never a
    pipe, so a killed child (whose tunnel helpers may inherit the
    descriptors) can never wedge THIS process draining it; without
    it, stdio is inherited (workload mode).

    This is the one copy of the bounded-subprocess mechanics for
    talking to a possibly-wedged tunneled accelerator, shared by
    bench._guard_backend and tools/tpu_window.py (probe and workload
    both).
    """
    import contextlib
    import signal
    import subprocess
    import tempfile

    with contextlib.ExitStack() as stack:
        kw: dict = {}
        if env is not None:
            kw['env'] = env
        errf = None
        if capture_stderr:
            errf = stack.enter_context(tempfile.TemporaryFile())
            kw.update(stdout=subprocess.DEVNULL, stderr=errf)
        proc = subprocess.Popen(argv, start_new_session=True, **kw)
        try:
            rc = proc.wait(timeout=budget_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()
            return 'timeout', '', -1
        if rc == 0:
            return 'ok', '', 0
        detail = ''
        if errf is not None:
            errf.seek(0)
            tail = errf.read().decode(errors='replace').strip()
            detail = (tail.splitlines()[-1:] or ['?'])[0]
        if rc < 0:
            # Signal-killed (OOM killer, tunnel-side abort, external
            # kill): distinct from a deterministic nonzero exit so
            # callers can retry it like a timeout instead of aborting
            # the hunt (tools/tpu_window.py).
            return 'killed', detail or ('signal %d' % (-rc,)), rc
        return 'error', detail, rc


def bounded_probe(code: str, budget_s: float) -> tuple[str, str, int]:
    """``bounded_run`` over ``python -c code`` with stderr capture —
    the probe form used against a possibly-wedged accelerator."""
    import sys

    return bounded_run([sys.executable, '-c', code], budget_s,
                       capture_stderr=True)


def force_cpu(n_devices: int | None = None) -> None:
    """Pin this process's JAX to the host CPU platform.

    With ``n_devices``, also request that many virtual CPU devices
    (``--xla_force_host_platform_device_count``) — only effective if
    the CPU backend has not been initialized yet.
    """
    if n_devices is not None:
        flags = os.environ.get('XLA_FLAGS', '')
        flag = f'--xla_force_host_platform_device_count={n_devices}'
        if '--xla_force_host_platform_device_count' in flags:
            flags = ' '.join(
                flag if f.startswith('--xla_force_host_platform_device_count')
                else f for f in flags.split())
        else:
            flags = (flags + ' ' + flag).strip()
        os.environ['XLA_FLAGS'] = flags
    os.environ['JAX_PLATFORMS'] = 'cpu'

    import jax

    jax.config.update('jax_platforms', 'cpu')
    try:  # drop the remote plugin's factory so backend discovery
        # cannot stall dialing a TPU the tests must not touch
        from jax._src import xla_bridge as xb

        xb._backend_factories.pop('axon', None)
    except Exception:
        pass
