"""Structured logging with child-context accretion.

The reference threads a bunyan logger through every layer: the client
accepts an injectable ``log`` option (reference: lib/client.js:34-45),
and each component derives a child logger that accretes key-value
context — component name, then zkAddress/zkPort on the connection
(reference: lib/connection-fsm.js:93-96), then sessionId once the
handshake lands (reference: lib/connection-fsm.js:209-211,
lib/zk-session.js:179-181) — so any line can be traced to its
connection and session without grepping for adjacent lines.

This is the stdlib-logging equivalent: a lightweight ``Logger`` facade
over a ``logging.Logger`` whose ``child(**ctx)`` returns a new facade
with merged context.  Context renders as a bracketed suffix on the
message and also travels structured on the record as ``zk_context``
(for JSON handlers).  Users may inject either a stdlib logger or an
existing facade, as with the reference's ``log`` option.
"""

from __future__ import annotations

import logging as _logging

#: bunyan's TRACE sits below DEBUG; register the level once.
TRACE = 5
_logging.addLevelName(TRACE, 'TRACE')


class Logger:
    """A context-accreting facade over a stdlib logger."""

    def __init__(self, base: '_logging.Logger | Logger | None' = None,
                 context: dict | None = None):
        if isinstance(base, Logger):
            context = {**base.context, **(context or {})}
            base = base.base
        self.base: _logging.Logger = (
            base if base is not None else _logging.getLogger('zkstream_tpu'))
        self.context: dict = dict(context or {})

    def child(self, **ctx) -> 'Logger':
        """A new facade with ``ctx`` merged over this one's context
        (the analogue of bunyan's ``log.child({...})``)."""
        return Logger(self.base, {**self.context, **ctx})

    @staticmethod
    def _render(msg: str, args: tuple) -> str:
        """Render ``msg % args`` with the mismatch fallback both _log
        and exception() share.  A format/arg mismatch must stay
        contained like stdlib logging's deferred formatting would —
        never raise into an FSM state handler."""
        if args:
            try:
                msg = msg % args
            except (TypeError, ValueError):
                msg = '%s %r' % (msg, args)
        return msg

    def _log(self, level: int, msg: str, *args) -> None:
        if not self.base.isEnabledFor(level):
            return
        # Render args BEFORE appending the context suffix: a context
        # value containing '%' (e.g. an IPv6 zone id in zkAddress) must
        # not be interpreted as a format directive.
        msg = self._render(msg, args)
        if self.context:
            msg += ' [%s]' % ' '.join(
                '%s=%s' % (k, v) for k, v in self.context.items())
        # stacklevel 3: hop over _log and the level-method wrapper so
        # %(filename)s/%(lineno)d point at the real call site.
        self.base.log(level, msg, stacklevel=3,
                      extra={'zk_context': dict(self.context)})

    def trace(self, msg: str, *args) -> None:
        self._log(TRACE, msg, *args)

    def debug(self, msg: str, *args) -> None:
        self._log(_logging.DEBUG, msg, *args)

    def info(self, msg: str, *args) -> None:
        self._log(_logging.INFO, msg, *args)

    def warning(self, msg: str, *args) -> None:
        self._log(_logging.WARNING, msg, *args)

    warn = warning

    def error(self, msg: str, *args) -> None:
        self._log(_logging.ERROR, msg, *args)

    def exception(self, msg: str, *args) -> None:
        """Error-level log with the ACTIVE exception's traceback
        appended — for except-blocks that swallow an error to keep a
        loop alive (e.g. the multihost cadence) but must not hide it."""
        if not self.base.isEnabledFor(_logging.ERROR):
            return
        import sys
        import traceback

        if sys.exc_info()[0] is None:
            # no active exception: format_exc() would append a
            # confusing 'NoneType: None' tail — plain error instead
            self._log(_logging.ERROR, '%s', self._render(msg, args))
            return
        # render the caller's args FIRST so a literal '%' in the
        # rendered message cannot collide with the traceback's %s slot
        # (same invariant _log keeps for context suffixes)
        self._log(_logging.ERROR, '%s\n%s', self._render(msg, args),
                  traceback.format_exc())

    def fatal(self, msg: str, *args) -> None:
        """Bunyan's top level (the reference logs at fatal before
        crash-on-bug throws)."""
        self._log(_logging.CRITICAL, msg, *args)
