"""Driver glue for the C load generator (tools/loadgen.c).

The bench families spawn ``zkloadgen`` instead of the Python read
workers by default — the Python arm decodes ~9k replies/s per worker
process, so every "server" ceiling it measured was actually the
client's (PROFILE.md round 15 carry; round 19 re-baselines).  This
module owns the build (via utils/native.py's graceful
skip-when-no-compiler discipline) and the knob surface:

- ``ZKSTREAM_LOADGEN``: ``c`` (default) drives benches with the C
  loadgen; ``py`` keeps the Python worker validator arm.
- ``ZKSTREAM_LOADGEN_THREADS``: epoll threads per loadgen process
  (default: auto = min(cores, 8)).
- ``ZKSTREAM_LOADGEN_PIPELINE``: outstanding ops per connection
  (default 16; the million-session campaign uses 1).
- ``ZKSTREAM_LOADGEN_RAMP``: handshakes/s for the connect wave
  (default 0 = unpaced).
- ``ZKSTREAM_LOADGEN_SRC_ADDRS``: loopback source addresses to spread
  connections over (default 0 = auto: one per ~20k sessions, with
  ``IP_BIND_ADDRESS_NO_PORT`` where the kernel has it) so a single
  host can open ~1M sockets without exhausting one address's ~28k
  ephemeral ports.

All knobs are documented in README "Load generation"; the zkanalyze
knob-drift baseline stays at zero.
"""

from __future__ import annotations

import os

from . import native


def mode() -> str:
    """``'c'`` (default) or ``'py'`` (the validator arm)."""
    m = os.environ.get('ZKSTREAM_LOADGEN', 'c').strip().lower()
    return 'py' if m == 'py' else 'c'


def available() -> str | None:
    """Build (if needed) and return the binary path, or None when the
    host has no compiler — callers fall back to the Python arm."""
    return native.build_loadgen()


def argv(servers, sessions, *, duration=None, count=None, mix=None,
         pipeline=None, threads=None, ramp=None, idle_ping=None,
         arm_watch=False, fanout_sets=None, setwatches_storm=False,
         path=None, data=None, stdio_sync=False, src_addrs=None,
         session_timeout_ms=None, close_sessions=False,
         ensure_path=True, quiet=True, cached=False,
         cached_write_ms=None) -> list[str] | None:
    """The zkloadgen command line for one run, env knobs applied.
    Returns None when the binary can't be built."""
    binary = available()
    if binary is None:
        return None
    cmd = [binary,
           '--servers', ','.join('%s:%d' % (h, p) for h, p in servers),
           '--sessions', str(int(sessions))]
    env = os.environ.get
    if duration is not None:
        cmd += ['--duration', str(float(duration))]
    if count is not None:
        cmd += ['--count', str(int(count))]
    if mix:
        cmd += ['--mix', mix]
    pipeline = pipeline if pipeline is not None else env(
        'ZKSTREAM_LOADGEN_PIPELINE')
    if pipeline is not None:
        cmd += ['--pipeline', str(int(pipeline))]
    threads = threads if threads is not None else env(
        'ZKSTREAM_LOADGEN_THREADS')
    if threads is not None:
        cmd += ['--threads', str(int(threads))]
    ramp = ramp if ramp is not None else env('ZKSTREAM_LOADGEN_RAMP')
    if ramp is not None:
        cmd += ['--ramp', str(float(ramp))]
    if idle_ping is not None:
        cmd += ['--idle-ping', str(float(idle_ping))]
    if arm_watch:
        cmd += ['--arm-watch']
    if fanout_sets:
        cmd += ['--fanout-sets', str(int(fanout_sets))]
    if setwatches_storm:
        cmd += ['--setwatches-storm']
    if path:
        cmd += ['--path', path]
    if data is not None:
        cmd += ['--data', str(int(data))]
    if stdio_sync:
        cmd += ['--stdio-sync']
    src_addrs = src_addrs if src_addrs is not None else env(
        'ZKSTREAM_LOADGEN_SRC_ADDRS')
    if src_addrs is not None:
        cmd += ['--src-addrs', str(int(src_addrs))]
    if session_timeout_ms is not None:
        cmd += ['--session-timeout', str(int(session_timeout_ms))]
    if close_sessions:
        cmd += ['--close-sessions']
    if not ensure_path:
        cmd += ['--no-ensure-path']
    if quiet:
        cmd += ['--quiet']
    if cached:
        cmd += ['--cached']
    if cached_write_ms is not None:
        cmd += ['--cached-write-ms', str(float(cached_write_ms))]
    return cmd
