"""Lightweight xid-correlated op tracing.

The metrics layer answers "how much / how slow in aggregate"; this
module answers "what happened to THAT request".  A :class:`Span` is
created per client op (client.py), threaded by xid through the
connection's pending-request table (io/connection.py) and stamped with
the reply's zxid when the reply routes back; the session layer records
notification deliveries into the same ring (io/session.py), so one
dump interleaves requests, replies, errors, and watch notifications in
arrival order.

Spans live in a bounded in-memory ring buffer (:class:`TraceRing`) —
fixed memory, no I/O, safe to leave on in production.  The chaos
campaign (io/faults.py, tests/test_chaos.py, ``chaos`` CLI) dumps the
ring alongside the failing seed, so a schedule failure arrives with
the exact request/reply interleaving that produced it instead of a
log-grepping session.
"""

from __future__ import annotations

import collections
import itertools
import json
import time


class Span:
    """One traced operation: request-side fields stamped at creation,
    reply-side fields stamped on completion."""

    __slots__ = ('span_id', 'kind', 'op', 'path', 'xid', 'zxid',
                 'backend', 'session_id', 'status', 'error',
                 't_wall', '_t0', 'duration_ms')

    def __init__(self, span_id: int, op: str, path: str | None = None,
                 kind: str = 'op'):
        self.span_id = span_id
        self.kind = kind          # 'op' | 'notification' | 'event'
        self.op = op
        self.path = path
        self.xid: int | None = None
        self.zxid: int | None = None
        self.backend: str | None = None
        self.session_id: str | None = None
        self.status: str = 'open'
        self.error: str | None = None
        self.t_wall = time.time()
        self._t0 = time.monotonic()
        self.duration_ms: float | None = None

    def finish(self, zxid: int | None = None, status: str = 'ok',
               error: str | None = None) -> None:
        """Close the span exactly once; a double-settle (teardown races
        in the connection) keeps the first outcome."""
        if self.status != 'open':
            return
        self.duration_ms = (time.monotonic() - self._t0) * 1000.0
        if zxid is not None:
            self.zxid = zxid
        self.status = status
        self.error = error

    def to_dict(self) -> dict:
        d = {'span': self.span_id, 'kind': self.kind, 'op': self.op,
             'status': self.status, 't_wall': round(self.t_wall, 6)}
        for field in ('path', 'xid', 'zxid', 'backend', 'session_id',
                      'error'):
            val = getattr(self, field)
            if val is not None:
                d[field] = val
        if self.duration_ms is not None:
            d['duration_ms'] = round(self.duration_ms, 3)
        return d

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return '<Span %s>' % (self.to_dict(),)


class TraceRing:
    """A bounded ring of recent spans: appends evict the oldest entry
    once ``capacity`` is reached, so memory is fixed regardless of op
    volume."""

    def __init__(self, capacity: int = 256):
        assert capacity > 0, capacity
        self.capacity = capacity
        self._ring: collections.deque[Span] = collections.deque(
            maxlen=capacity)
        self._ids = itertools.count(1)

    def __len__(self) -> int:
        return len(self._ring)

    def start(self, op: str, path: str | None = None,
              kind: str = 'op') -> Span:
        span = Span(next(self._ids), op, path, kind=kind)
        self._ring.append(span)
        return span

    def note(self, op: str, path: str | None = None,
             zxid: int | None = None, kind: str = 'event',
             **fields) -> Span:
        """Record an instantaneous event (notification delivery, state
        edge) as a zero-duration span."""
        span = self.start(op, path, kind=kind)
        for name, val in fields.items():
            setattr(span, name, val)
        span.finish(zxid=zxid)
        return span

    def spans(self) -> list[Span]:
        return list(self._ring)

    def dump(self) -> list[dict]:
        """The ring's contents, oldest first, as JSON-ready dicts."""
        return [s.to_dict() for s in self._ring]

    def dump_json(self, indent: int | None = None) -> str:
        return json.dumps(self.dump(), indent=indent)

    def clear(self) -> None:
        self._ring.clear()


def format_spans(spans: list[dict], limit: int | None = None) -> str:
    """Render dumped spans as aligned text lines for failure reports
    (newest-last; ``limit`` keeps assertion messages bounded)."""
    if limit is not None and len(spans) > limit:
        spans = spans[-limit:]
    lines = []
    for s in spans:
        dur = ('%8.2fms' % s['duration_ms']
               if s.get('duration_ms') is not None else '      open')
        lines.append(
            '  #%-4d %-12s xid=%-6s zxid=%-6s %-7s %s %s%s'
            % (s['span'], s['op'], s.get('xid', '-'),
               s.get('zxid', '-'), s['status'], dur,
               s.get('path') or '',
               (' [%s]' % s['error']) if s.get('error') else ''))
    return '\n'.join(lines)
