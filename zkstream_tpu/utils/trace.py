"""Lightweight causal tracing: client op spans + member span chains.

The metrics layer answers "how much / how slow in aggregate"; this
module answers "what happened to THAT request".  A :class:`Span` is
created per client op (client.py), threaded by xid through the
connection's pending-request table (io/connection.py) and stamped with
the reply's zxid when the reply routes back; the session layer records
notification deliveries into the same ring (io/session.py), so one
dump interleaves requests, replies, errors, and watch notifications in
arrival order.

Since the server grew its own trace plane, every ensemble member also
carries a ring (server/server.py ``ZKServer.trace``): a write txn
leaves a **zxid-keyed span chain** across the ensemble — the batch
decode (``SRV_DECODE``), the store apply (``COMMIT``), the WAL append
(``WAL_APPEND``), the group fsync its ack rode (``GROUP_FSYNC``, one
span shared by every txn in the barrier, stamped with the batch size),
the replication push per follower (``REPL_PUSH``), each follower's
apply (``APPLY``), and the watch fan-out delivery (``FANOUT``, watch
count + flushed bytes).  :func:`merge_timelines` joins the client ring
and any number of member rings **by zxid** into one causal timeline;
:func:`format_timeline` renders it.  ``python -m zkstream_tpu
timeline`` demos the merge end to end, and both chaos tiers dump the
member rings next to the client ring on failure.

Spans live in a bounded in-memory ring buffer (:class:`TraceRing`) —
fixed memory, no I/O, safe to leave on in production; overwrites are
counted in :attr:`TraceRing.dropped` (the ``zk_trace_ring_dropped``
mntr row).  The chaos campaign (io/faults.py, tests/test_chaos.py,
``chaos`` CLI) dumps the rings alongside the failing seed, so a
schedule failure arrives with the exact cross-member path of the
lost or duplicated write instead of a log-grepping session.

``TRACE_SCHEMA`` versions every JSON emission of spans
(``chaos --trace-out``, the ``trce`` admin word, ``timeline --json``);
:meth:`Span.to_dict` emits its keys in one fixed order so dumps are
byte-stable for a given span.
"""

from __future__ import annotations

import collections
import itertools
import json
import time

#: Version stamp for every JSON emission of span dumps.  Bump when
#: span fields or their meaning change; consumers key on it.
#: Schema 2: member rings (``member``/``batch``/``nbytes``/``detail``
#: fields, server-side ops), stable-ordered ``Span.to_dict``.
TRACE_SCHEMA = 2

#: ``to_dict`` emission order (after the four always-present keys):
#: fixed so a span serializes byte-identically regardless of which
#: setattr path populated it.
_OPTIONAL_FIELDS = ('path', 'xid', 'zxid', 'backend', 'session_id',
                    'member', 'batch', 'nbytes', 'detail', 'error')


def server_trace_default() -> bool:
    """Process-wide default for the server-side trace plane (member
    rings + tick ledger).  ``ZKSTREAM_NO_SERVER_TRACE=1`` disables it
    — the untraced arm of the bench overhead A/B (`bench.py
    --traceov`), mirroring the cork/WAL/watchtable kill switches."""
    import os
    return os.environ.get('ZKSTREAM_NO_SERVER_TRACE') != '1'


class Span:
    """One traced operation: request-side fields stamped at creation,
    reply-side fields stamped on completion."""

    __slots__ = ('span_id', 'kind', 'op', 'path', 'xid', 'zxid',
                 'backend', 'session_id', 'status', 'error',
                 't_wall', '_t0', 'duration_ms',
                 'member', 'batch', 'nbytes', 'detail', '_on_slow')

    def __init__(self, span_id: int, op: str, path: str | None = None,
                 kind: str = 'op'):
        self.span_id = span_id
        self.kind = kind  # 'op'|'notification'|'event'|'server'|...
        self.op = op
        self.path = path
        self.xid: int | None = None
        self.zxid: int | None = None
        self.backend: str | None = None
        self.session_id: str | None = None
        #: Which ensemble member recorded this span (None = client).
        self.member: str | None = None
        #: Batch size, where the span covers several frames/txns
        #: (decode batch, group-fsync barrier, fan-out watch count).
        self.batch: int | None = None
        #: Bytes the span moved (WAL record, flushed fan-out bytes).
        self.nbytes: int | None = None
        #: Free-form qualifier (log-entry op, follower token).
        self.detail: str | None = None
        self.status: str = 'open'
        self.error: str | None = None
        self.t_wall = time.time()
        self._t0 = time.monotonic()
        self.duration_ms: float | None = None
        #: Armed by a ring with a slow-op threshold: called once with
        #: the span when finish() measures a duration at/over it.
        self._on_slow = None

    def finish(self, zxid: int | None = None, status: str = 'ok',
               error: str | None = None) -> None:
        """Close the span exactly once; a double-settle (teardown races
        in the connection) keeps the first outcome."""
        if self.status != 'open':
            return
        self.duration_ms = (time.monotonic() - self._t0) * 1000.0
        if zxid is not None:
            self.zxid = zxid
        self.status = status
        self.error = error
        hook = self._on_slow
        if hook is not None:
            self._on_slow = None
            hook(self)

    def to_dict(self) -> dict:
        """JSON-ready dict, keys in one fixed order (insertion order
        survives ``json.dumps``), so a span's serialization is stable
        across processes and runs."""
        d = {'span': self.span_id, 'kind': self.kind, 'op': self.op,
             'status': self.status, 't_wall': round(self.t_wall, 6)}
        for field in _OPTIONAL_FIELDS:
            val = getattr(self, field)
            if val is not None:
                d[field] = val
        if self.duration_ms is not None:
            d['duration_ms'] = round(self.duration_ms, 3)
        return d

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return '<Span %s>' % (self.to_dict(),)


class TraceRing:
    """A bounded ring of recent spans: appends evict the oldest entry
    once ``capacity`` is reached — memory is fixed regardless of op
    volume — and :attr:`dropped` counts the evictions so a scrape can
    tell a quiet ring from one that wrapped.  ``member`` stamps every
    span recorded here with the owning ensemble member's id (None for
    the client ring)."""

    def __init__(self, capacity: int = 256,
                 member: str | None = None):
        assert capacity > 0, capacity
        self.capacity = capacity
        self.member = member
        #: ring overwrites since construction (the mntr
        #: ``zk_trace_ring_dropped`` row)
        self.dropped = 0
        #: Slow-op digest threshold in ms, or None (off).  When set,
        #: every span settled on this ring whose duration meets it is
        #: handed to :attr:`on_slow` — the black-box plane's hook
        #: (utils/blackbox.py persists the span's causal chain).
        self.slow_ms: float | None = None
        self.on_slow = None
        self._ring: collections.deque[Span] = collections.deque(
            maxlen=capacity)
        self._ids = itertools.count(1)

    def __len__(self) -> int:
        return len(self._ring)

    def _slow_settled(self, span: Span) -> None:
        """Span.finish() callback: apply the threshold (the hook fires
        on every settle; sub-threshold spans stop here)."""
        if (self.slow_ms is not None and self.on_slow is not None
                and span.duration_ms is not None
                and span.duration_ms >= self.slow_ms):
            self.on_slow(span)

    def start(self, op: str, path: str | None = None,
              kind: str = 'op') -> Span:
        span = Span(next(self._ids), op, path, kind=kind)
        if self.member is not None:
            span.member = self.member
        if self.slow_ms is not None:
            span._on_slow = self._slow_settled
        if len(self._ring) >= self.capacity:
            self.dropped += 1       # the append below evicts one
        self._ring.append(span)
        return span

    def note(self, op: str, path: str | None = None,
             zxid: int | None = None, kind: str = 'event',
             **fields) -> Span:
        """Record an instantaneous event (notification delivery, state
        edge, a member-side txn stage) as an already-settled span.
        ``fields`` land last, so an explicit ``duration_ms=`` (a
        pre-measured stage, e.g. WAL_RECOVER or GROUP_FSYNC)
        overrides the 0 the instant close stamps.

        Built inline rather than via start()+finish(): this is the
        server hot path (a COMMIT + WAL_APPEND note per write txn),
        and skipping the open-span bookkeeping roughly halves the
        cost."""
        span = Span.__new__(Span)
        span.span_id = next(self._ids)
        span.kind = kind
        span.op = op
        span.path = path
        span.xid = None
        span.zxid = zxid
        span.backend = None
        span.session_id = None
        span.member = self.member
        span.batch = None
        span.nbytes = None
        span.detail = None
        span.status = 'ok'
        span.error = None
        span.t_wall = time.time()
        span._t0 = 0.0
        span.duration_ms = 0.0
        span._on_slow = None        # already settled; checked below
        for name, val in fields.items():
            setattr(span, name, val)
        if len(self._ring) >= self.capacity:
            self.dropped += 1       # the append below evicts one
        self._ring.append(span)
        if (self.slow_ms is not None
                and span.duration_ms >= self.slow_ms):
            self._slow_settled(span)
        return span

    def spans(self) -> list[Span]:
        return list(self._ring)

    def open_spans(self) -> list[Span]:
        """Spans still unsettled — after teardown there must be none
        (the chaos campaigns assert it; an op evicted from the pending
        table without a settle is a span-leak bug)."""
        return [s for s in self._ring if s.status == 'open']

    def dump(self) -> list[dict]:
        """The ring's contents, oldest first, as JSON-ready dicts."""
        return [s.to_dict() for s in self._ring]

    def dump_json(self, indent: int | None = None) -> str:
        return json.dumps(self.dump(), indent=indent)

    def clear(self) -> None:
        self._ring.clear()


def format_spans(spans: list[dict], limit: int | None = None) -> str:
    """Render dumped spans as aligned text lines for failure reports
    (newest-last; ``limit`` keeps assertion messages bounded)."""
    if limit is not None and len(spans) > limit:
        spans = spans[-limit:]
    lines = []
    for s in spans:
        dur = ('%8.2fms' % s['duration_ms']
               if s.get('duration_ms') is not None else '      open')
        lines.append(
            '  #%-4d %-12s xid=%-6s zxid=%-6s %-7s %s %s%s'
            % (s['span'], s['op'], s.get('xid', '-'),
               s.get('zxid', '-'), s['status'], dur,
               s.get('path') or '',
               (' [%s]' % s['error']) if s.get('error') else ''))
    return '\n'.join(lines)


# ---------------------------------------------------------------------
# Cross-ring merge: the zxid-keyed causal timeline.
# ---------------------------------------------------------------------

#: Causal stage rank within one zxid: in-process hops settle within
#: the same millisecond, so wall time alone cannot order the chain —
#: the pipeline's actual order does.  Client op spans (submit) lead,
#: the client-side notification delivery trails.
_STAGE_RANK = {
    'COMMIT': 2,
    'WAL_APPEND': 3,
    'GROUP_FSYNC': 4,
    'REPL_PUSH': 5,
    'APPLY': 6,
    'FANOUT': 7,
    'NOTIFICATION': 8,
}
_STAGE_DEFAULT = 9


def _stage(span: dict) -> int:
    rank = _STAGE_RANK.get(span.get('op', ''))
    if rank is not None:
        return rank
    if span.get('kind') == 'op':
        return 1                    # client submit leads its zxid
    return _STAGE_DEFAULT


def merge_timelines(rings: dict[str, list[dict]]) -> list[dict]:
    """Merge span dumps from several rings into one causal timeline.

    ``rings`` maps a source name ('client', 'member:1', ...) to that
    ring's :meth:`TraceRing.dump`.  Every span carrying a zxid joins
    the timeline, stamped with its source (a span's own ``member``
    field wins over the ring name), ordered by
    ``(zxid, causal stage, wall time)`` — so a lagging follower's
    apply span, recorded long after later transactions, still merges
    back into its own zxid's group in causal position."""
    out: list[dict] = []
    for source, spans in rings.items():
        # a member-qualified ring name wins over the span's own member
        # field: a caller merging two same-id members keys them apart
        # ('member:0@hostB:2181', timeline --live) and that distinction
        # must survive into the rendered source
        qualified = source.startswith('member:')
        for s in spans:
            if s.get('zxid') is None:
                continue
            e = dict(s)
            member = s.get('member')
            e['source'] = ('member:%s' % (member,)
                           if member is not None and not qualified
                           else source)
            out.append(e)
    out.sort(key=lambda e: (e['zxid'], _stage(e),
                            e.get('t_wall', 0.0)))
    return out


def format_timeline(entries: list[dict],
                    limit: int | None = None) -> str:
    """Render a merged timeline as aligned text, one causal step per
    line, zxid-grouped (oldest first)."""
    if limit is not None and len(entries) > limit:
        entries = entries[-limit:]
    lines = []
    last_zxid = None
    for e in entries:
        zxid = e['zxid']
        zcol = ('zxid %-6d' % zxid) if zxid != last_zxid \
            else '     %-6s' % ''
        last_zxid = zxid
        extra = []
        if e.get('batch') is not None:
            extra.append('batch=%d' % e['batch'])
        if e.get('nbytes') is not None:
            extra.append('%dB' % e['nbytes'])
        if e.get('detail'):
            extra.append(str(e['detail']))
        if e.get('xid') is not None:
            extra.append('xid=%d' % e['xid'])
        if e.get('duration_ms'):
            extra.append('%.2fms' % e['duration_ms'])
        if e.get('error'):
            extra.append('[%s]' % e['error'])
        lines.append(('%s %-10s %-12s %-7s %s %s'
                      % (zcol, e.get('source', '?'), e['op'],
                         e.get('status', ''), e.get('path') or '-',
                         ' '.join(extra))).rstrip())
    return '\n'.join(lines)
