"""Loader for the native host codec (native/zkwire.cpp).

Builds the shared library on first use with the ambient ``g++`` and
binds it via ctypes.  Design constraints, in order:

- **Never block the event loop.**  ``get_lib()`` only dlopens an
  already-built artifact; when a build is needed it is kicked off on a
  daemon thread and ``get_lib()`` returns None until it lands, so the
  connection path silently runs pure-Python in the meantime.
- **Stale artifacts can't poison the process.**  The artifact name
  embeds the ABI version (``libzkwire.v1.so``); an old build is simply
  a different filename that is never dlopened, sidestepping glibc's
  same-path handle caching.
- **Graceful degradation.**  No compiler, failed build, failed load →
  None, and callers keep the pure-Python implementations — mirroring
  how the reference runs on nothing but the OS TCP stack (SURVEY.md §2:
  zero native components required).

``ZKSTREAM_NO_NATIVE=1`` forces the pure-Python path (tests A/B the two
implementations with it).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

log = logging.getLogger('zkstream_tpu.native')

_ABI_VERSION = 1

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False
_builder: threading.Thread | None = None


def _root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def source_path() -> str:
    return os.path.join(_root(), 'native', 'zkwire.cpp')


def lib_path() -> str:
    return os.path.join(_root(), 'native',
                        'libzkwire.v%d.so' % _ABI_VERSION)


def build() -> str | None:
    """Compile the library if missing or stale; return its path or
    None.  Synchronous — call from tests/tools, not the event loop
    (:func:`get_lib` wraps it in a background thread)."""
    src, out = source_path(), lib_path()
    if not os.path.exists(src):
        return None
    if (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    tmp = out + '.tmp.%d' % os.getpid()
    cmd = ['g++', '-O2', '-shared', '-fPIC', '-std=c++17', src, '-o', tmp]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.info('native build unavailable: %s', e)
        return None
    if r.returncode != 0:
        log.warning('native build failed: %s', r.stderr.strip())
        return None
    os.replace(tmp, out)  # atomic: concurrent builders can't mix halves
    return out


def _bind(path: str) -> ctypes.CDLL | None:
    lib = ctypes.CDLL(path)
    lib.zkwire_abi_version.restype = ctypes.c_int32
    lib.zkwire_abi_version.argtypes = []
    if lib.zkwire_abi_version() != _ABI_VERSION:
        log.warning('libzkwire ABI mismatch (version-named artifact '
                    'should make this impossible)')
        return None
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.zkwire_frame_scan.restype = ctypes.c_int32
    lib.zkwire_frame_scan.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        i32p, i32p, i32p]
    return lib


def _try_load() -> None:
    """Bind the on-disk artifact if present and current (fast: one
    stat + dlopen).  Sets _lib/_load_failed; caller holds _lock."""
    global _lib, _load_failed
    out, src = lib_path(), source_path()
    if not (os.path.exists(out) and os.path.exists(src)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return
    try:
        _lib = _bind(out)
    except OSError as e:
        log.warning('libzkwire load failed: %s', e)
        _lib = None
    if _lib is None:
        _load_failed = True


def get_lib() -> ctypes.CDLL | None:
    """The bound library, or None if unavailable (yet).

    Non-blocking: when the artifact is missing the build runs on a
    daemon thread and this returns None until a later call finds the
    artifact ready."""
    global _builder
    if os.environ.get('ZKSTREAM_NO_NATIVE') == '1':
        return None
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        _try_load()
        if _lib is not None or _load_failed:
            return _lib
        if _builder is None or not _builder.is_alive():
            _builder = threading.Thread(
                target=_build_or_latch, name='zkwire-build', daemon=True)
            _builder.start()
        return None


def _build_or_latch() -> None:
    """Background-build the C-ABI library; a failed compile latches
    ``_load_failed`` so later ``get_lib`` calls don't respawn gcc for
    the life of the process."""
    global _load_failed
    if build() is None:
        with _lock:
            _load_failed = True


def ensure_lib(timeout: float = 120.0) -> ctypes.CDLL | None:
    """Blocking variant for tests/tools: build synchronously and bind."""
    if os.environ.get('ZKSTREAM_NO_NATIVE') == '1':
        return None
    if build() is None:
        return None
    return get_lib()


# -- CPython-extension decoder (native/zkwire_ext.c) ------------------
#
# Separate artifact from the C-ABI scanner: it links against the
# interpreter ABI (Python.h), decodes whole accumulation buffers into
# packet dicts (framing + reply bodies in one C pass — the boundary the
# profile in tools/profile_hotpath.py points at), and is loaded with the
# same version-named-artifact / background-build discipline.

_EXT_ABI_VERSION = 10

_ext = None
_ext_load_failed = False
_ext_builder: threading.Thread | None = None


def ext_source_path() -> str:
    return os.path.join(_root(), 'native', 'zkwire_ext.c')


def ext_path() -> str:
    import sysconfig
    tag = sysconfig.get_config_var('SOABI') or 'abi3'
    return os.path.join(_root(), 'native', '_zkwire_ext.v%d.%s.so'
                        % (_EXT_ABI_VERSION, tag))


def build_ext() -> str | None:
    """Compile the extension if missing or stale; return path or None."""
    import sysconfig
    src, out = ext_source_path(), ext_path()
    if not os.path.exists(src):
        return None
    if (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    tmp = out + '.tmp.%d' % os.getpid()
    cmd = ['gcc', '-O2', '-shared', '-fPIC',
           '-I', sysconfig.get_paths()['include'], src, '-o', tmp]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.info('native ext build unavailable: %s', e)
        return None
    if r.returncode != 0:
        log.warning('native ext build failed: %s', r.stderr.strip())
        return None
    os.replace(tmp, out)
    return out


#: opcode -> reply-body-layout enum shared with zkwire_ext.c (keep in
#: sync with records._RESP_READERS / _EMPTY_RESPONSES).
_EXT_LAYOUTS = {
    'SET_WATCHES': 0, 'SET_WATCHES2': 0, 'ADD_WATCH': 0, 'PING': 0,
    'SYNC': 0, 'DELETE': 0, 'CLOSE_SESSION': 0, 'AUTH': 0,
    'GET_CHILDREN': 1, 'GET_CHILDREN2': 2, 'CREATE': 3, 'GET_ACL': 4,
    'GET_DATA': 5, 'EXISTS': 6, 'SET_DATA': 6, 'NOTIFICATION': 7,
    'MULTI': 8,
}

#: opcode -> request-body-layout enum (keep in sync with
#: records._REQ_READERS): 0 empty, 1 path, 2 path+watch, 3 create,
#: 4 delete, 5 set_data, 6 set_watches, 7 multi, 8 add_watch,
#: 9 set_watches2.
_EXT_REQ_LAYOUTS = {
    'GET_CHILDREN': 2, 'GET_CHILDREN2': 2, 'GET_DATA': 2, 'EXISTS': 2,
    'CREATE': 3, 'DELETE': 4, 'GET_ACL': 1, 'SET_DATA': 5, 'SYNC': 1,
    'SET_WATCHES': 6, 'CLOSE_SESSION': 0, 'PING': 0, 'MULTI': 7,
    'ADD_WATCH': 8, 'SET_WATCHES2': 9,
}

#: Opcodes the spec tier decodes but the extension deliberately PUNTS
#: (decode_stream returns kind='UNSUPPORTED' at the frame boundary and
#: PacketCodec hands the rest of the buffer to the Python spec tier).
#: Empty since the MULTI layouts landed (the PR 12 carry closed):
#: every spec reader has a C layout in both directions; the punt
#: MACHINERY stays for the next variable-shape opcode.  The sync test
#: in tests/test_native_ext.py holds ``layouts | punts == spec
#: readers``; byte-identical MULTI A/B lives in tests/test_multi.py.
_EXT_PUNT_OPS = frozenset()


def ext_setup_args() -> tuple:
    """The argument tuple for ``_zkwire_ext.setup`` — shared by the
    loader and out-of-band harnesses (tools/asan_check.py) so a
    signature change cannot leave them disagreeing."""
    from ..protocol import records
    from ..protocol.consts import (
        CreateFlag,
        ErrCode,
        KeeperState,
        NotificationType,
        OpCode,
        Perm,
    )

    return (
        records.Stat, records.ACL, records.Id, Perm, CreateFlag,
        {int(e): e.name for e in ErrCode},
        {int(t): t.name for t in NotificationType},
        {int(s): s.name for s in KeeperState},
        dict(_EXT_LAYOUTS),
        {int(OpCode[name]): (name, layout)
         for name, layout in _EXT_REQ_LAYOUTS.items()},
        {int(o): o.name for o in OpCode},
        {e.name: int(e) for e in ErrCode},
        {t.name: int(t) for t in NotificationType},
        {s.name: int(s) for s in KeeperState},
        {o.name: int(o) for o in OpCode},
    )


def _bind_ext(path: str):
    import importlib.machinery
    import importlib.util

    loader = importlib.machinery.ExtensionFileLoader('_zkwire_ext', path)
    spec = importlib.util.spec_from_file_location(
        '_zkwire_ext', path, loader=loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    if mod.abi_version() != _EXT_ABI_VERSION:
        log.warning('zkwire_ext ABI mismatch')
        return None
    mod.setup(*ext_setup_args())
    return mod


def _try_load_ext() -> None:
    global _ext, _ext_load_failed
    out, src = ext_path(), ext_source_path()
    if not (os.path.exists(out) and os.path.exists(src)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return
    try:
        _ext = _bind_ext(out)
    except (OSError, ImportError) as e:
        log.warning('zkwire_ext load failed: %s', e)
        _ext = None
    if _ext is None:
        _ext_load_failed = True


def get_ext():
    """The bound extension module, or None if unavailable (yet).
    Non-blocking, same contract as :func:`get_lib`."""
    global _ext_builder
    if os.environ.get('ZKSTREAM_NO_NATIVE') == '1':
        return None
    with _lock:
        if _ext is not None or _ext_load_failed:
            return _ext
        _try_load_ext()
        if _ext is not None or _ext_load_failed:
            return _ext
        if _ext_builder is None or not _ext_builder.is_alive():
            _ext_builder = threading.Thread(
                target=_build_ext_or_latch, name='zkwire-ext-build',
                daemon=True)
            _ext_builder.start()
        return None


def _build_ext_or_latch() -> None:
    """Background-build the extension; latch failure like
    :func:`_build_or_latch`."""
    global _ext_load_failed
    if build_ext() is None:
        with _lock:
            _ext_load_failed = True


def ensure_ext():
    """Blocking variant for tests/tools: build synchronously and bind."""
    if os.environ.get('ZKSTREAM_NO_NATIVE') == '1':
        return None
    if build_ext() is None:
        return None
    return get_ext()


# -- C load generator (tools/loadgen.c) -------------------------------
#
# A standalone binary, not a shared library: it drives the real wire
# protocol over raw sockets (the measuring instrument the bench
# families spawn instead of the Python read workers — README "Load
# generation").  Same discipline as the other two artifacts:
# version-named output, atomic tmp+rename publish, graceful None when
# the host has no compiler so `make check`/tier-1 never hard-fail on
# a codec-less image.

_LOADGEN_VERSION = 1


def loadgen_source_path() -> str:
    return os.path.join(_root(), 'tools', 'loadgen.c')


def loadgen_path() -> str:
    return os.path.join(_root(), 'native',
                        'zkloadgen.v%d' % _LOADGEN_VERSION)


def build_loadgen() -> str | None:
    """Compile the load generator if missing or stale; return its
    path or None.  Synchronous (tools/bench only, never the event
    loop)."""
    src, out = loadgen_source_path(), loadgen_path()
    if not os.path.exists(src):
        return None
    if (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    tmp = out + '.tmp.%d' % os.getpid()
    cmd = ['gcc', '-O2', '-pthread', src, '-o', tmp]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.info('loadgen build unavailable: %s', e)
        return None
    if r.returncode != 0:
        log.warning('loadgen build failed: %s', r.stderr.strip())
        return None
    os.replace(tmp, out)
    return out


class NativeFrameScanner:
    """ctypes facade over zkwire_frame_scan for one connection.

    ``scan`` reads the caller's accumulation buffer zero-copy (ctypes
    ``from_buffer`` on the bytearray) and returns ``(spans, resid,
    bad_at)``: (start, size) body spans, the cursor after the last
    complete frame, and the offset of an invalid length prefix (or
    None).  The caller must not mutate the bytearray during the call
    (single-threaded asyncio guarantees that here)."""

    __slots__ = ('_lib', '_cap', '_starts', '_sizes')

    def __init__(self, lib: ctypes.CDLL, cap: int = 256):
        self._lib = lib
        self._cap = cap
        self._starts = (ctypes.c_int32 * cap)()
        self._sizes = (ctypes.c_int32 * cap)()

    def scan(self, buf: bytearray, max_packet: int):
        n_total = len(buf)
        if n_total < 4:
            return [], 0, None
        arr = (ctypes.c_uint8 * n_total).from_buffer(buf)
        try:
            addr = ctypes.addressof(arr)
            spans: list[tuple[int, int]] = []
            base = 0
            while True:
                resid = ctypes.c_int32(0)
                n = self._lib.zkwire_frame_scan(
                    addr + base, n_total - base, max_packet, self._cap,
                    self._starts, self._sizes, ctypes.byref(resid))
                if n < 0:
                    bad = base + resid.value
                    return spans, bad, bad
                spans.extend((base + self._starts[i], self._sizes[i])
                             for i in range(n))
                base += resid.value
                if n < self._cap:
                    return spans, base, None
        finally:
            del arr  # release the buffer export before caller mutates
