"""Event-loop plumbing shared by the runtime components."""

from __future__ import annotations

import asyncio
import socket


def ambient_loop() -> asyncio.AbstractEventLoop:
    """The running loop, or — outside a running loop — the thread's set
    loop.

    ``Client.start()`` — and the client/pool FSM transitions it drives
    synchronously — may legitimately run before the loop starts
    spinning, queuing work the loop will process once entered;
    ``asyncio.get_running_loop`` alone would forbid that pattern, while
    bare ``get_event_loop`` is deprecated when no loop is set.  This
    helper keeps both cases working and never creates an implicit loop
    inside callbacks.  (Connections themselves are constructed only
    inside pool tasks, so ``io/connection.py`` uses the stricter
    ``get_running_loop`` throughout.)
    """
    try:
        return asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.get_event_loop()


def set_nodelay(endpoint) -> None:
    """Set ``TCP_NODELAY`` on an asyncio transport or StreamWriter.

    ZooKeeper traffic is small request/reply frames; with Nagle on, the
    kernel delays a short frame behind an unacked one, adding an RTT-ish
    stall per op under write-heavy load.  Any write batching should be
    the send plane's explicit per-tick cork (io/sendplane.py), not the
    kernel's implicit one.  Best-effort: non-TCP endpoints (unix
    sockets, test doubles without a real socket) are left alone."""
    try:
        sock = endpoint.get_extra_info('socket')
        if sock is not None:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except (OSError, ValueError, AttributeError):
        pass
