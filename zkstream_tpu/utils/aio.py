"""Event-loop plumbing shared by the runtime components."""

from __future__ import annotations

import asyncio


def ambient_loop() -> asyncio.AbstractEventLoop:
    """The running loop, or — outside a running loop — the thread's set
    loop.

    ``Client.start()`` — and the client/pool FSM transitions it drives
    synchronously — may legitimately run before the loop starts
    spinning, queuing work the loop will process once entered;
    ``asyncio.get_running_loop`` alone would forbid that pattern, while
    bare ``get_event_loop`` is deprecated when no loop is set.  This
    helper keeps both cases working and never creates an implicit loop
    inside callbacks.  (Connections themselves are constructed only
    inside pool tasks, so ``io/connection.py`` uses the stricter
    ``get_running_loop`` throughout.)
    """
    try:
        return asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.get_event_loop()
