"""RLIMIT_NOFILE handling for million-session serving (README "Load
generation").

The first honest million-session campaign (PROFILE.md round 19) made
fd limits a first-class failure mode instead of a mystery EMFILE
deep in accept(2): every server entry point lifts the soft limit as
far as the host allows **at startup**, and when the host cap is the
binding constraint the error says so by name — which limit, what it
fits, and which knob raises it (the hard limit / ``fs.nr_open``
sysctl need privilege; this code never silently degrades).

The C loadgen does the same dance on its side (tools/loadgen.c
``raise_nofile``) and reports the outcome in its summary JSON under
``caps`` / ``binding_constraint``.
"""

from __future__ import annotations

import logging

log = logging.getLogger('zkstream_tpu.fdlimit')


def raise_nofile(need: int | None = None) -> tuple[int, int]:
    """Lift the soft RLIMIT_NOFILE toward the hard limit (and, where
    the process has the privilege, the hard limit toward ``need``).
    Returns the resulting ``(soft, hard)``.  Never raises: a host
    that refuses stays at its cap and the caller decides whether
    that's binding (:func:`headroom_error`)."""
    import resource
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    want = hard if need is None else max(need, soft)
    if need is not None and want > hard:
        # raising the hard limit needs CAP_SYS_RESOURCE and is
        # bounded by fs.nr_open; try, keep what sticks
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (want, want))
        except (ValueError, OSError):
            pass
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        want = min(want, hard)
    if soft < want:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))
        except (ValueError, OSError):
            pass
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    return soft, hard


def headroom_error(need: int, *, reserve: int = 256) -> str | None:
    """A clear binding-constraint message when the current soft limit
    cannot fit ``need`` descriptors (plus a reserve for WAL segments,
    listeners, pipes), or None when there is room.  The message names
    the limit and the fix — it is what lands in logs and in bench
    cell JSON as ``binding_constraint``."""
    import resource
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    fit = soft - reserve
    if fit >= need:
        return None
    return ('RLIMIT_NOFILE: soft/hard %d/%d fits %d connections '
            '(wanted %d); raise the hard limit (needs privilege) '
            'and fs.nr_open to go higher' % (soft, hard, fit, need))
