"""Benchmark: batched wire-decode throughput, TPU data plane vs scalar codec.

The reference publishes no benchmark numbers (BASELINE.md — no
benchmarks/ dir, README is API docs only), so the measurable baseline
is defined here: decode a fleet of framed ZooKeeper reply streams —
frame slicing + reply-header parse + xid routing + max-zxid session
reduction, exactly the per-connection hot path of
lib/zk-streams.js:39-99 / lib/connection-fsm.js:213-229 — and compare

  baseline:  the scalar bytes-loop codec (zkstream_tpu.protocol), the
             same implementation idiom as the reference's JavaScript
             (per-byte buffer walking on one core), and
  value:     the batched tensor pipeline (zkstream_tpu.ops) on the
             default JAX device (TPU under the driver).

Prints ONE JSON line:
  {"metric": "wire_decode_throughput", "value": <MiB/s>,
   "unit": "MiB/s", "vs_baseline": <tpu/scalar ratio>}
"""

from __future__ import annotations

import json
import struct
import sys
import time

import numpy as np

B = 32768        # streams (connections) per tick
FRAMES = 64      # frames per stream
BODY = 84        # body bytes per frame -> 104-byte frames
REPEATS = 30     # dispatches per timing round (x4 rounds, min taken)


DATA_LEN = 12    # GET_DATA payload bytes per reply


def _fleet():
    """Vectorized fleet builder: [B, L] framed streams of **valid
    GET_DATA replies** — reply header, then buffer(data) + Stat
    (reference layout: lib/zk-buffer.js:281-331,353-357,428-442) —
    so the full-decode benchmark decodes real bodies, not noise
    (32768 x 64 x 104 B = 208 MiB at the default shape).

    A shape sweep on the tunneled v5e showed the step time pinned at
    ~90-140 us from 13 MiB up to 208 MiB per tick — the
    remote-dispatch latency floor — so the tick must be fleet-proxy
    sized for the device to be doing meaningful work per dispatch; at
    208 MiB/tick the decode sustains ~1.7-2.9 TiB/s vs ~0.1 TiB/s at
    the round-1 2048x64 shape."""
    rng = np.random.RandomState(42)
    frame_len = 4 + 16 + BODY
    L = FRAMES * frame_len
    v = np.zeros((B, FRAMES, frame_len), np.uint8)

    def be(field, width, out):
        shifts = np.arange(8 * (width - 1), -1, -8, dtype=np.int64)
        out[...] = ((field[..., None] >> shifts) & 0xFF).astype(np.uint8)

    def ri(lo, hi):
        return rng.randint(lo, hi, (B, FRAMES)).astype(np.int64)

    zxid = ri(1, 1 << 40)
    be(np.full((B, FRAMES), 16 + BODY, np.int64), 4, v[:, :, 0:4])
    # xids: sequential per stream from a random base, like the
    # connection FSM's allocator — a reply xid is unique in flight
    # (duplicates would poison the pop-on-reply xid map)
    xid = (rng.randint(1, 1 << 19, (B, 1)).astype(np.int64)
           + np.arange(FRAMES, dtype=np.int64))
    be(xid, 4, v[:, :, 4:8])
    be(zxid, 8, v[:, :, 8:16])                   # zxid (err stays 0)
    # GET_DATA body: buffer(len, data) then the 68-byte Stat
    be(np.full((B, FRAMES), DATA_LEN, np.int64), 4, v[:, :, 20:24])
    v[:, :, 24:24 + DATA_LEN] = rng.randint(
        0, 256, (B, FRAMES, DATA_LEN), dtype=np.uint8)
    s = 24 + DATA_LEN                            # Stat start
    be(ri(1, 1 << 40), 8, v[:, :, s:s + 8])          # czxid
    be(zxid, 8, v[:, :, s + 8:s + 16])               # mzxid
    be(ri(1, 1 << 41), 8, v[:, :, s + 16:s + 24])    # ctime
    be(ri(1, 1 << 41), 8, v[:, :, s + 24:s + 32])    # mtime
    be(ri(0, 1 << 10), 4, v[:, :, s + 32:s + 36])    # version
    be(ri(0, 1 << 10), 4, v[:, :, s + 36:s + 40])    # cversion
    be(ri(0, 1 << 10), 4, v[:, :, s + 40:s + 44])    # aversion
    # ephemeralOwner stays 0
    be(np.full((B, FRAMES), DATA_LEN, np.int64), 4,
       v[:, :, s + 52:s + 56])                       # dataLength
    # numChildren stays 0
    be(ri(1, 1 << 40), 8, v[:, :, s + 60:s + 68])    # pzxid
    buf = v.reshape(B, L)
    lens = np.full((B,), L, np.int32)
    streams = [buf[i].tobytes() for i in range(B)]
    return buf, lens, streams


def bench_scalar(streams) -> float:
    """Scalar protocol-tick baseline, MiB/s: length-prefix walk +
    reply-header parse + routing counts + max-zxid per stream —
    exactly the work the device tick metric does (headers only, no
    body materialization, so the comparison is equal-work), as an
    interpreted per-byte loop in the reference's idiom
    (lib/zk-streams.js:39-64 + lib/connection-fsm.js:213-229)."""
    ln_s = struct.Struct('>i')
    hdr = struct.Struct('>iqi')
    total = sum(len(s) for s in streams)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        for s in streams:
            off, n = 0, len(s)
            max_zxid = 0
            n_notif = n_ping = n_err = 0
            while n - off >= 4:
                (ln,) = ln_s.unpack_from(s, off)
                if ln < 0 or ln > 16 << 20 or n - off < 4 + ln:
                    break
                xid, zxid, err = hdr.unpack_from(s, off + 4)
                if xid == -1:
                    n_notif += 1
                elif xid == -2:
                    n_ping += 1
                else:
                    if err:
                        n_err += 1
                    if zxid > max_zxid:
                        max_zxid = zxid
                off += 4 + ln
    dt = time.perf_counter() - t0
    return total * reps / dt / (1024 * 1024)


SCALAR_FULL_STREAMS = 1024   # subset for the interpreted full decode
                             # (throughput is per-byte; ~65k frames is
                             # plenty and keeps the bench under budget)


def _xid_maps(sub):
    """Per-stream xid -> opcode maps, as each connection's send side
    would have recorded them (lib/zk-streams.js:145)."""
    hdr_xid = struct.Struct('>i')
    maps = []
    frame_len = 4 + 16 + BODY
    for s in sub:
        m = {}
        for off in range(0, len(s), frame_len):
            (xid,) = hdr_xid.unpack_from(s, off + 4)
            m[xid] = 'GET_DATA'
        maps.append(m)
    return maps


def bench_scalar_full(streams):
    """Scalar **full decode** baseline, MiB/s: framing + reply header +
    opcode-dispatched body parse into packet dicts (data bytes + Stat
    records) — the complete per-frame receive work of the reference
    client (lib/zk-buffer.js:275-442), interpreted Python in the
    reference's idiom.  Returns (MiB/s, first decoded packet) — the
    packet seeds the device full-decode correctness gate."""
    from zkstream_tpu.protocol.framing import FrameDecoder
    from zkstream_tpu.protocol.jute import JuteReader
    from zkstream_tpu.protocol.records import read_response

    sub = streams[:SCALAR_FULL_STREAMS]
    maps = _xid_maps(sub)
    total = sum(len(s) for s in sub)
    pkt0 = None
    t0 = time.perf_counter()
    for s, m in zip(sub, maps):
        dec = FrameDecoder(use_native=False)
        mm = dict(m)
        for body in dec.feed(s):
            pkt = read_response(JuteReader(body), mm)
            if pkt0 is None:
                pkt0 = pkt
    dt = time.perf_counter() - t0
    return total / dt / (1024 * 1024), pkt0


def bench_ext_full(streams) -> float | None:
    """The repo's own C-extension full decode over the same subset —
    context line so the flagship ratio is read against both the
    reference-idiom interpreted loop and this framework's native
    scalar path."""
    from zkstream_tpu.utils import native

    ext = native.ensure_ext()
    if ext is None:
        return None
    from zkstream_tpu.protocol.consts import MAX_PACKET

    sub = streams[:SCALAR_FULL_STREAMS]
    maps = _xid_maps(sub)
    total = sum(len(s) for s in sub)
    t0 = time.perf_counter()
    for s, m in zip(sub, maps):
        pkts, _consumed, kind, _msg = ext.decode_responses(
            s, dict(m), MAX_PACKET)
        assert kind is None and len(pkts) == FRAMES
    dt = time.perf_counter() - t0
    return total / dt / (1024 * 1024)


def bench_tensor(buf, lens, pkt0) -> tuple[float, float, float]:
    """Tensor pipeline MiB/s on the default JAX device: the protocol
    tick (header decode + routing) and the **full decode** (tick +
    batched reply-body parse, ops/replies.py — the work of
    lib/zk-buffer.js:275-442).  Returns (tick_mibs, full_mibs).

    The tick times the fused Pallas kernel (ops/pallas_scan.py) and
    the pure-jnp pipeline (whose XLA scan gathers only header bytes —
    the usual winner on TPU; also the fallback where Pallas cannot
    lower, e.g. plain CPU jax) and reports the best; both are
    property-tested equivalent (tests/test_pallas.py).

    All timing runs BEFORE any device->host readback: on a tunneled
    remote TPU, the first readback of a computation output permanently
    flips the client into per-dispatch synchronization (~60x slower
    dispatches for the rest of the process), so the correctness gates
    — including the full-decode equality check against the scalar
    codec's packet — run after every candidate has been timed."""
    import jax
    import jax.numpy as jnp

    from zkstream_tpu.ops.pipeline import (
        wire_pipeline_step,
        wire_pipeline_step_pallas,
    )
    from zkstream_tpu.ops.replies import (
        parse_list_bodies,
        parse_reply_bodies,
    )

    jb, jl = jnp.asarray(buf), jnp.asarray(lens)

    def full(b, l):
        st = wire_pipeline_step(b, l, max_frames=FRAMES)
        bd = parse_reply_bodies(b, st.starts, st.sizes,
                                max_data=16, max_path=8)
        return st, bd

    def full_deployed(b, l):
        # the configuration the SHIPPED ingest runs (io/ingest.py
        # defaults): 256-byte data/path planes plus the speculative
        # children/ACL list planes — every layout parsed at every
        # frame, exactly the deployed device-bodies work
        st = wire_pipeline_step(b, l, max_frames=FRAMES)
        bd = parse_reply_bodies(b, st.starts, st.sizes,
                                max_data=256, max_path=256)
        lb = parse_list_bodies(b, st.starts, st.sizes,
                               max_children=16, max_name=64,
                               max_acls=4, max_scheme=16, max_id=64)
        return st, bd, lb

    candidates = [
        ('pallas', lambda b, l: wire_pipeline_step_pallas(
            b, l, max_frames=FRAMES, block_rows=64), REPEATS),
        ('jnp', lambda b, l: wire_pipeline_step(
            b, l, max_frames=FRAMES), REPEATS),
        ('full', full, REPEATS),
        # deployed widths cost ~20x the toy planes in output bytes;
        # fewer repeats keep the run inside the time/HBM budget
        ('full-deployed', full_deployed, max(4, REPEATS // 5)),
    ]
    total = int(lens.sum())
    timed = []
    for name, fn, reps in candidates:
        try:
            step = jax.jit(fn)
            out = step(jb, jl)  # compile + warm
            jax.block_until_ready(out)
        except Exception as e:  # pallas unsupported on this backend
            print(f'# {name} path unavailable: {e}', file=sys.stderr)
            continue
        def leaf(o):
            # keep only one tiny output leaf per repeat: it becomes
            # ready when the whole computation does (valid timing),
            # while the big body planes free as dispatches retire —
            # holding REPEATS full-decode outputs (0.5-4 GiB each)
            # exhausts device memory
            # WireStats (namedtuple) or a (st, bodies...) tuple
            return (o.n_frames if hasattr(o, 'n_frames')
                    else o[0].n_frames)
        dts = []
        for _ in range(4):
            t0 = time.perf_counter()
            outs = [leaf(step(jb, jl)) for _ in range(reps)]
            jax.block_until_ready(outs)
            dts.append((time.perf_counter() - t0) / reps)
        mibs = total / min(dts) / (1024 * 1024)
        timed.append((name, mibs, out))

    tick_best = full_best = full_deployed_best = 0.0
    for name, mibs, out in timed:
        # correctness gates, after ALL timing (first readback poisons
        # dispatch): a decode mismatch must fail the benchmark, not
        # skip the path
        if name == 'full':
            _gate_full_decode(out[:2], pkt0)
            full_best = mibs
        elif name == 'full-deployed':
            _gate_full_decode(out[:2], pkt0)
            # the list planes must also have parsed: a GET_DATA body
            # is not a children/ACL list, so the speculative parse
            # flags every frame not-ok — the planes ran, found nothing
            lb = out[2]
            assert not bool(np.asarray(lb.ch_ok).any()), \
                'list plane misparse'
            full_deployed_best = mibs
        else:
            assert int(np.asarray(out.n_frames).sum()) == B * FRAMES, \
                f'{name} decode mismatch'
            tick_best = max(tick_best, mibs)
        print(f'# {name} path: {mibs:.2f} MiB/s', file=sys.stderr)
    # the skip-on-exception escape is for the OPTIONAL pallas path;
    # the mandatory paths must have timed, else the run reports a
    # zero flagship instead of failing
    assert tick_best > 0, 'no tick path timed'
    assert full_best > 0, 'full-decode path never timed'
    assert full_deployed_best > 0, 'deployed-width path never timed'
    return tick_best, full_best, full_deployed_best


def _gate_full_decode(out, pkt0) -> None:
    """The full-decode output must agree with the scalar codec: every
    frame found, every data field located, every Stat parsed, and frame
    (0, 0) equal field-for-field to the scalar codec's packet."""
    from zkstream_tpu.ops.bytesops import i64pair_to_int

    st, bd = out
    assert int(np.asarray(st.n_frames).sum()) == B * FRAMES, \
        'full decode lost frames'
    data_len = np.asarray(bd.data_len)
    assert (data_len == DATA_LEN).all(), 'full decode data_len mismatch'
    valid = np.asarray(bd.stat_after_data.valid)
    assert valid.all(), 'full decode Stat coverage mismatch'
    sad = bd.stat_after_data
    assert pkt0['opcode'] == 'GET_DATA'
    s0 = pkt0['stat']
    for fld in ('mzxid', 'czxid', 'pzxid', 'ctime', 'mtime'):
        got = i64pair_to_int(
            np.asarray(getattr(sad, fld + '_hi'))[0, 0],
            np.asarray(getattr(sad, fld + '_lo'))[0, 0])
        assert got == getattr(s0, fld), (fld, got, getattr(s0, fld))
    for fld in ('version', 'cversion', 'aversion', 'dataLength',
                'numChildren'):
        got = int(np.asarray(getattr(sad, fld))[0, 0])
        assert got == getattr(s0, fld), (fld, got, getattr(s0, fld))
    got_data = bytes(np.asarray(bd.data)[0, 0, :DATA_LEN])
    assert got_data == pkt0['data'], 'full decode data bytes mismatch'


CLIENT_SCALES = (32, 128)  # fleet sizes for the runtime bench (the
                           # crossover sweep, CROSSOVER.md, shows the
                           # batched path winning from ~128 conns)
OPS_TOTAL = 1920           # measured ops per workload, fleet-wide


def _percentiles(lat_ms):
    lat_ms = sorted(lat_ms)

    def pct(p):
        return lat_ms[min(len(lat_ms) - 1,
                          int(p / 100.0 * len(lat_ms)))]
    return pct(50), pct(99)


async def _client_ops_run(mode: str, n_clients: int) -> dict:
    """One end-to-end runtime measurement: ops/sec and latency
    percentiles for get/set/create plus a watch fan-out, with
    ``n_clients`` concurrent clients against the in-process server.

    Modes: ``python`` (pure-Python scalar codec, the reference-idiom
    baseline), ``native`` (C++ frame scanner), ``ingest`` (batched
    TPU decode via FleetIngest)."""
    import asyncio

    from zkstream_tpu import Client
    from zkstream_tpu.server import ZKServer

    ingest = None
    use_native = None
    if mode == 'ingest':
        from zkstream_tpu.io.ingest import FleetIngest
        # bypass_bytes=0: this mode exists to measure the batched
        # device pipeline end-to-end; the production small-tick
        # crossover would route this workload through the scalar codec
        # (which the python/native modes already measure).  max_frames
        # fleet-sized per CROSSOVER.md (oversized per-stream slots are
        # padding waste at fleet scale).
        ingest = FleetIngest(body_mode='host', max_frames=8,
                             bypass_bytes=0)
    elif mode == 'native':
        use_native = True
    elif mode == 'python':
        use_native = False

    loop = asyncio.get_running_loop()
    srv = await ZKServer().start()
    clients = [Client(address='127.0.0.1', port=srv.port,
                      session_timeout=30000, ingest=ingest,
                      use_native_codec=use_native)
               for _ in range(n_clients)]
    for c in clients:
        c.start()
    await asyncio.gather(*[c.wait_connected(timeout=30)
                           for c in clients])
    out = {'mode': mode, 'conns': n_clients}
    try:
        await clients[0].create('/b', b'x' * 64)
        if ingest is not None:
            # compile every (batch, length) bucket the workload can
            # touch up front: the bench measures the steady state, and
            # production servers do the same at startup (prewarm docs)
            bp = 8
            while bp <= n_clients:
                for nb in (None, 512):
                    await ingest.prewarm(bp, nb)
                bp *= 2

        # Warm the path before timing: connection steady state, and —
        # for the ingest — the jit cache across the padded batch-size
        # buckets the tick loop will hit.  Tolerant of a transient
        # disconnect (a client mid-resume raises ZKNotConnectedError;
        # on this single shared core a scheduling blip can trip one).
        from zkstream_tpu.protocol.errors import ZKNotConnectedError

        async def warm(c):
            for _attempt in range(3):
                try:
                    return await c.get('/b')
                except ZKNotConnectedError:
                    await c.wait_connected(timeout=30)
            return await c.get('/b')  # reconnected on the last wait
        for _ in range(5):
            await asyncio.gather(*[warm(c) for c in clients])

        async def timed(coro_fn, n):
            lat = []
            for _ in range(n):
                t0 = loop.time()
                await coro_fn()
                lat.append((loop.time() - t0) * 1000.0)
            return lat

        async def measure(name, coro_of, n_per_client):
            t0 = loop.time()
            lats = await asyncio.gather(*[
                timed(coro_of(c, i), n_per_client)
                for i, c in enumerate(clients)])
            dt = loop.time() - t0
            flat = [x for l in lats for x in l]
            p50, p99 = _percentiles(flat)
            out[name] = {
                'ops_per_sec': round(len(flat) / dt, 1),
                'p50_ms': round(p50, 3), 'p99_ms': round(p99, 3)}

        per = max(8, OPS_TOTAL // n_clients)
        await measure('get', lambda c, i: lambda: c.get('/b'), per)
        await measure('set',
                      lambda c, i: lambda: c.set('/b', b'y' * 64),
                      per // 2)
        seqs = [0] * n_clients

        def mk_create(c, i):
            async def run():
                seqs[i] += 1
                await c.create('/c%d-%d' % (i, seqs[i]), b'')
            return run
        await measure('create', mk_create, per // 4)

        # watch fan-out: every client watches one node; one set fires
        # n_clients notifications + re-arm reads through the stack.
        # Arming a dataChanged watch on an existing node emits once
        # immediately (the arming read) — wait those out and reset so
        # the timed window measures only the real notifications.
        fired = []
        armed = loop.create_future()
        done = loop.create_future()

        def on_fire(*a):
            fired.append(1)
            if len(fired) >= n_clients:
                if not armed.done():
                    armed.set_result(None)
                elif len(fired) >= n_clients and not done.done():
                    done.set_result(None)
        for c in clients:
            c.watcher('/b').on('dataChanged', on_fire)
        await asyncio.wait_for(armed, 10)   # all arm-time emits in
        await asyncio.sleep(0.2)            # all watches re-armed
        fired.clear()
        t0 = loop.time()
        await clients[0].set('/b', b'z' * 64)
        await asyncio.wait_for(done, 10)
        dt = loop.time() - t0
        out['watch_fanout'] = {
            'events': len(fired),
            'events_per_sec': round(len(fired) / dt, 1),
            'total_ms': round(dt * 1000.0, 2)}
        if ingest is not None:
            out['ingest_ticks'] = ingest.ticks
            out['ingest_scalar_ticks'] = ingest.ticks_scalar
            # nonzero = a bucket miss sent timed ops through the
            # scalar drain while its program compiled; published so
            # 'ingest'-labeled numbers are honest about it
            out['ingest_warming_ticks'] = ingest.ticks_warming
            out['ingest_frames'] = ingest.frames_routed
    finally:
        await asyncio.gather(*[c.close() for c in clients])
        await srv.stop()
    return out


def bench_client_ops() -> None:
    """End-to-end runtime numbers (VERDICT r1 items 1/8): the full
    asyncio client stack against the in-process server, per codec
    mode.  Secondary metrics: printed to stderr, one JSON line per
    mode, after the flagship decode numbers are already measured (the
    readbacks here would poison remote-TPU dispatch timing)."""
    import asyncio

    from zkstream_tpu.utils import native

    modes = ['python']
    if native.ensure_lib() is not None:
        modes.append('native')
    modes.append('ingest')
    results: dict = {}
    # Interleaved best-of-2 per cell: this image runs everything on one
    # shared core, so a single sequential pass can swing +-30% on
    # scheduling noise alone.
    for _ in range(2):
        for n in CLIENT_SCALES:
            for mode in modes:
                try:
                    r = asyncio.run(_client_ops_run(mode, n))
                except Exception as e:
                    # a failed round must not kill the already-printed
                    # headline metric; the other round still reports
                    print('# client_ops %s@%d round failed: %r'
                          % (mode, n, e), file=sys.stderr)
                    continue
                key = (mode, n)
                if (key not in results
                        or r['get']['ops_per_sec']
                        > results[key]['get']['ops_per_sec']):
                    results[key] = r
    for n in CLIENT_SCALES:
        for mode in modes:
            if (mode, n) in results:
                print('# client_ops %s'
                      % json.dumps(results[(mode, n)]), file=sys.stderr)
    for n in CLIENT_SCALES:
        cell = {m: results[(m, n)] for m in modes if (m, n) in results}
        if not cell:
            continue
        base = cell.get('python', {}).get('get', {}).get('ops_per_sec')
        best_mode = max(cell,
                        key=lambda m: cell[m]['get']['ops_per_sec'])
        best = cell[best_mode]['get']['ops_per_sec']
        print(json.dumps({
            'metric': 'client_get_ops_per_sec',
            'conns': n,
            'value': best,
            'unit': 'ops/s',
            'vs_baseline': round(best / base, 3) if base else None,
            'mode': best_mode,
        }), file=sys.stderr)


def _guard_backend(timeout_s: float = 240.0) -> None:
    """Probe the default JAX backend in a SUBPROCESS before this
    process touches jax: a wedged tunneled-TPU backend has been
    observed to block device enumeration for 20+ minutes and then
    fail, which would kill the run before the flagship metric prints.
    If the probe cannot enumerate devices, fall back to the host CPU
    backend so the benchmark completes (the numbers then measure the
    CPU backend and say so).

    The probe pays one extra backend spin-up on a healthy run — the
    price of a guaranteed headline when the tunnel is wedged; set
    ZKSTREAM_BENCH_NO_PROBE=1 to skip it.  No pipes: stderr goes to a
    temp file so a killed probe (whose tunnel helpers may inherit the
    descriptors) can never wedge THIS process draining them, and the
    probe runs in its own session so the whole group is killed on
    timeout."""
    import os
    import signal
    import subprocess
    import tempfile

    if os.environ.get('ZKSTREAM_BENCH_NO_PROBE') == '1':
        return
    reason = None
    with tempfile.TemporaryFile() as errf:
        proc = subprocess.Popen(
            [sys.executable, '-c', 'import jax; jax.devices()'],
            stdout=subprocess.DEVNULL, stderr=errf,
            start_new_session=True)
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()
            reason = 'probe timed out after %.0fs' % timeout_s
        else:
            if rc == 0:
                return
            errf.seek(0)
            tail = errf.read().decode(errors='replace').strip()
            reason = 'probe failed: %s' % (
                tail.splitlines()[-1:] or ['?'])[0]
    print('# default JAX backend unavailable (%s); falling back to '
          'the host CPU backend' % (reason,), file=sys.stderr)
    from zkstream_tpu.utils.platform import force_cpu
    force_cpu(n_devices=1)


def main() -> None:
    _guard_backend()
    # Initialize the host CPU backend FIRST: the fleet ingest's
    # latency-aware placement wants it, and creating a second PJRT
    # client after heavy accelerator use has been observed to block on
    # a tunneled TPU (the ingest guards with a timeout, but eager init
    # here makes the fast path deterministic).
    try:
        import jax
        jax.devices('cpu')
    except Exception as e:  # pragma: no cover - environment-specific
        print('# cpu backend unavailable: %s' % (e,), file=sys.stderr)

    buf, lens, streams = _fleet()
    scalar = bench_scalar(streams)
    scalar_full, pkt0 = bench_scalar_full(streams)
    ext_full = bench_ext_full(streams)
    tick, full, full_deployed = bench_tensor(buf, lens, pkt0)
    print(f'# scalar tick baseline: {scalar:.2f} MiB/s over {B} '
          f'streams x {FRAMES} frames (headers only, equal work)',
          file=sys.stderr)
    print(f'# scalar full-decode baseline: {scalar_full:.2f} MiB/s '
          f'over {SCALAR_FULL_STREAMS} streams (framing + header + '
          f'body -> packet dicts)', file=sys.stderr)
    if ext_full is not None:
        print(f'# C-extension full decode: {ext_full:.2f} MiB/s '
              f'(this framework\'s own native scalar path)',
          file=sys.stderr)
    # Roofline note: MiB/s here counts WIRE BYTES PROCESSED per
    # second, not bytes touched — the header scan gathers ~20 B and
    # the full decode ~(20 + data + Stat) B of each 104 B frame, so
    # multi-TiB/s figures are consistent with v5e's ~0.8 TB/s HBM
    # (the decode reads each wire byte at most once but is PAID per
    # frame, and most wire bytes are payload it only slices).
    print('# note: MiB/s = wire bytes processed; see roofline note '
          'in bench.py main()', file=sys.stderr)
    # protocol-tick metric (headers + routing; the r1/r2 series)
    backend = jax.default_backend()
    print(json.dumps({
        'metric': 'wire_decode_throughput',
        'value': round(tick, 2),
        'unit': 'MiB/s',
        'vs_baseline': round(tick / scalar, 3),
        'backend': backend,
    }), flush=True)
    # toy-width full decode (the r3 headline's configuration, kept for
    # series comparability)
    print(json.dumps({
        'metric': 'wire_full_decode_toy_width',
        'value': round(full, 2),
        'unit': 'MiB/s',
        'vs_baseline': round(full / scalar_full, 3),
        'widths': 'data16/path8',
        'backend': backend,
    }), flush=True)
    try:
        bench_client_ops()
    except Exception as e:  # secondary metrics never sink the run
        print('# client_ops stage failed: %r' % (e,), file=sys.stderr)
    sys.stderr.flush()
    # the flagship: FULL decode at the DEPLOYED body configuration
    # (io/ingest.py defaults: 256-byte data/path planes + children/ACL
    # list planes) vs the scalar codec doing the same complete work —
    # printed last so the driver records it as the round's headline
    # (VERDICT r3 next #2: the headline must be the number the shipped
    # configuration would produce)
    print(json.dumps({
        'metric': 'wire_full_decode_throughput',
        'value': round(full_deployed, 2),
        'unit': 'MiB/s',
        'vs_baseline': round(full_deployed / scalar_full, 3),
        'widths': 'data256/path256/ch16x64/acl4',
        'toy_width_mibs': round(full, 2),
        'backend': backend,
    }), flush=True)


if __name__ == '__main__':
    main()
