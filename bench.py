"""Benchmark: batched wire-decode throughput, TPU data plane vs scalar codec.

The reference publishes no benchmark numbers (BASELINE.md — no
benchmarks/ dir, README is API docs only), so the measurable baseline
is defined here: decode a fleet of framed ZooKeeper reply streams —
frame slicing + reply-header parse + xid routing + max-zxid session
reduction, exactly the per-connection hot path of
lib/zk-streams.js:39-99 / lib/connection-fsm.js:213-229 — and compare

  baseline:  the scalar bytes-loop codec (zkstream_tpu.protocol), the
             same implementation idiom as the reference's JavaScript
             (per-byte buffer walking on one core), and
  value:     the batched tensor pipeline (zkstream_tpu.ops) on the
             default JAX device (TPU under the driver).

Prints ONE JSON line:
  {"metric": "wire_decode_throughput", "value": <MiB/s>,
   "unit": "MiB/s", "vs_baseline": <tpu/scalar ratio>}
"""

from __future__ import annotations

import json
import struct
import sys
import time

import numpy as np

B = 2048         # streams (connections) per tick
FRAMES = 64      # frames per stream
BODY = 84        # body bytes per frame -> 104-byte frames
REPEATS = 30     # dispatches per timing round (x4 rounds, min taken)


def _fleet():
    """Vectorized fleet builder: [B, L] framed reply streams with
    random xids/zxids/bodies (2048 x 64 x 104 B = 13.0 MiB at the
    default shape — large enough that the tensor path is compute-, not
    dispatch-, bound)."""
    rng = np.random.RandomState(42)
    frame_len = 4 + 16 + BODY
    L = FRAMES * frame_len
    v = np.zeros((B, FRAMES, frame_len), np.uint8)

    def be(field, width, out):
        shifts = np.arange(8 * (width - 1), -1, -8, dtype=np.int64)
        out[...] = ((field[..., None] >> shifts) & 0xFF).astype(np.uint8)

    be(np.full((B, FRAMES), 16 + BODY, np.int64), 4, v[:, :, 0:4])
    be(rng.randint(1, 1 << 20, (B, FRAMES)).astype(np.int64), 4,
       v[:, :, 4:8])
    be(rng.randint(1, 1 << 40, (B, FRAMES)).astype(np.int64), 8,
       v[:, :, 8:16])
    v[:, :, 20:] = rng.randint(0, 256, (B, FRAMES, BODY), dtype=np.uint8)
    buf = v.reshape(B, L)
    lens = np.full((B,), L, np.int32)
    streams = [buf[i].tobytes() for i in range(B)]
    return buf, lens, streams


def bench_scalar(streams) -> float:
    """Scalar codec MiB/s: framing + header parse + routing counts +
    max-zxid tracking per stream, pure python like the reference's JS."""
    from zkstream_tpu.protocol.framing import FrameDecoder

    hdr = struct.Struct('>iqi')
    total = sum(len(s) for s in streams)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        for s in streams:
            # use_native=False: the baseline is the reference-idiom
            # interpreted scalar loop, not the C++ host codec
            dec = FrameDecoder(use_native=False)
            max_zxid = 0
            n_notif = n_ping = n_err = 0
            for body in dec.feed(s):
                xid, zxid, err = hdr.unpack_from(body, 0)
                if xid == -1:
                    n_notif += 1
                elif xid == -2:
                    n_ping += 1
                else:
                    if err:
                        n_err += 1
                    if zxid > max_zxid:
                        max_zxid = zxid
    dt = time.perf_counter() - t0
    return total * reps / dt / (1024 * 1024)


def bench_tensor(buf, lens) -> float:
    """Tensor pipeline MiB/s on the default JAX device.

    Times the fused Pallas kernel (ops/pallas_scan.py) and the pure-jnp
    pipeline (whose XLA scan gathers only header bytes — the usual
    winner on TPU; also the fallback where Pallas cannot lower, e.g.
    plain CPU jax) and reports the best; both are property-tested
    equivalent (tests/test_pallas.py).

    All timing runs BEFORE any device->host readback: on a tunneled
    remote TPU, the first readback of a computation output permanently
    flips the client into per-dispatch synchronization (~60x slower
    dispatches for the rest of the process), so the correctness gates
    run after every candidate has been timed."""
    import jax
    import jax.numpy as jnp

    from zkstream_tpu.ops.pipeline import (
        wire_pipeline_step,
        wire_pipeline_step_pallas,
    )

    jb, jl = jnp.asarray(buf), jnp.asarray(lens)
    candidates = [
        ('pallas', lambda b, l: wire_pipeline_step_pallas(
            b, l, max_frames=FRAMES, block_rows=128)),
        ('jnp', lambda b, l: wire_pipeline_step(
            b, l, max_frames=FRAMES)),
    ]
    total = int(lens.sum())
    timed = []
    for name, fn in candidates:
        try:
            step = jax.jit(fn)
            out = step(jb, jl)  # compile + warm
            jax.block_until_ready(out)
        except Exception as e:  # pallas unsupported on this backend
            print(f'# {name} path unavailable: {e}', file=sys.stderr)
            continue
        dts = []
        for _ in range(4):
            t0 = time.perf_counter()
            outs = [step(jb, jl) for _ in range(REPEATS)]
            jax.block_until_ready(outs)
            dts.append((time.perf_counter() - t0) / REPEATS)
        mibs = total / min(dts) / (1024 * 1024)
        timed.append((name, mibs, out))

    best = 0.0
    for name, mibs, out in timed:
        # correctness gate, after ALL timing (first readback poisons
        # dispatch): a decode mismatch must fail the benchmark, not
        # skip the path
        assert int(np.asarray(out.n_frames).sum()) == B * FRAMES, \
            f'{name} decode mismatch'
        print(f'# {name} path: {mibs:.2f} MiB/s', file=sys.stderr)
        best = max(best, mibs)
    return best


def main() -> None:
    buf, lens, streams = _fleet()
    scalar = bench_scalar(streams)
    tensor = bench_tensor(buf, lens)
    print(json.dumps({
        'metric': 'wire_decode_throughput',
        'value': round(tensor, 2),
        'unit': 'MiB/s',
        'vs_baseline': round(tensor / scalar, 3),
    }))
    print(f'# scalar baseline: {scalar:.2f} MiB/s over {B} streams x '
          f'{FRAMES} frames', file=sys.stderr)


if __name__ == '__main__':
    main()
