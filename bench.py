"""Benchmark: batched wire-decode throughput, TPU data plane vs scalar codec.

The reference publishes no benchmark numbers (BASELINE.md — no
benchmarks/ dir, README is API docs only), so the measurable baseline
is defined here: decode a fleet of framed ZooKeeper reply streams —
frame slicing + reply-header parse + xid routing + max-zxid session
reduction, exactly the per-connection hot path of
lib/zk-streams.js:39-99 / lib/connection-fsm.js:213-229 — and compare

  baseline:  the scalar bytes-loop codec (zkstream_tpu.protocol), the
             same implementation idiom as the reference's JavaScript
             (per-byte buffer walking on one core), and
  value:     the batched tensor pipeline (zkstream_tpu.ops) on the
             default JAX device (TPU under the driver).

Prints ONE JSON line:
  {"metric": "wire_decode_throughput", "value": <MiB/s>,
   "unit": "MiB/s", "vs_baseline": <tpu/scalar ratio>}
"""

from __future__ import annotations

import json
import struct
import sys
import time

import numpy as np

B = 32768        # streams (connections) per tick
FRAMES = 64      # frames per stream
BODY = 84        # body bytes per frame -> 104-byte frames
REPEATS = 30     # dispatches per timing round (x4 rounds, min taken)


def _fleet():
    """Vectorized fleet builder: [B, L] framed reply streams with
    random xids/zxids/bodies (32768 x 64 x 104 B = 208 MiB at the
    default shape).  A shape sweep on the tunneled v5e showed the step
    time pinned at ~90-140 us from 13 MiB up to 208 MiB per tick — the
    remote-dispatch latency floor — so the tick must be fleet-proxy
    sized for the device to be doing meaningful work per dispatch; at
    208 MiB/tick the decode sustains ~1.7-2.9 TiB/s vs ~0.1 TiB/s at
    the round-1 2048x64 shape."""
    rng = np.random.RandomState(42)
    frame_len = 4 + 16 + BODY
    L = FRAMES * frame_len
    v = np.zeros((B, FRAMES, frame_len), np.uint8)

    def be(field, width, out):
        shifts = np.arange(8 * (width - 1), -1, -8, dtype=np.int64)
        out[...] = ((field[..., None] >> shifts) & 0xFF).astype(np.uint8)

    be(np.full((B, FRAMES), 16 + BODY, np.int64), 4, v[:, :, 0:4])
    be(rng.randint(1, 1 << 20, (B, FRAMES)).astype(np.int64), 4,
       v[:, :, 4:8])
    be(rng.randint(1, 1 << 40, (B, FRAMES)).astype(np.int64), 8,
       v[:, :, 8:16])
    v[:, :, 20:] = rng.randint(0, 256, (B, FRAMES, BODY), dtype=np.uint8)
    buf = v.reshape(B, L)
    lens = np.full((B,), L, np.int32)
    streams = [buf[i].tobytes() for i in range(B)]
    return buf, lens, streams


def bench_scalar(streams) -> float:
    """Scalar codec MiB/s: framing + header parse + routing counts +
    max-zxid tracking per stream, pure python like the reference's JS."""
    from zkstream_tpu.protocol.framing import FrameDecoder

    hdr = struct.Struct('>iqi')
    total = sum(len(s) for s in streams)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        for s in streams:
            # use_native=False: the baseline is the reference-idiom
            # interpreted scalar loop, not the C++ host codec
            dec = FrameDecoder(use_native=False)
            max_zxid = 0
            n_notif = n_ping = n_err = 0
            for body in dec.feed(s):
                xid, zxid, err = hdr.unpack_from(body, 0)
                if xid == -1:
                    n_notif += 1
                elif xid == -2:
                    n_ping += 1
                else:
                    if err:
                        n_err += 1
                    if zxid > max_zxid:
                        max_zxid = zxid
    dt = time.perf_counter() - t0
    return total * reps / dt / (1024 * 1024)


def bench_tensor(buf, lens) -> float:
    """Tensor pipeline MiB/s on the default JAX device.

    Times the fused Pallas kernel (ops/pallas_scan.py) and the pure-jnp
    pipeline (whose XLA scan gathers only header bytes — the usual
    winner on TPU; also the fallback where Pallas cannot lower, e.g.
    plain CPU jax) and reports the best; both are property-tested
    equivalent (tests/test_pallas.py).

    All timing runs BEFORE any device->host readback: on a tunneled
    remote TPU, the first readback of a computation output permanently
    flips the client into per-dispatch synchronization (~60x slower
    dispatches for the rest of the process), so the correctness gates
    run after every candidate has been timed."""
    import jax
    import jax.numpy as jnp

    from zkstream_tpu.ops.pipeline import (
        wire_pipeline_step,
        wire_pipeline_step_pallas,
    )

    jb, jl = jnp.asarray(buf), jnp.asarray(lens)
    candidates = [
        ('pallas', lambda b, l: wire_pipeline_step_pallas(
            b, l, max_frames=FRAMES, block_rows=128)),
        ('jnp', lambda b, l: wire_pipeline_step(
            b, l, max_frames=FRAMES)),
    ]
    total = int(lens.sum())
    timed = []
    for name, fn in candidates:
        try:
            step = jax.jit(fn)
            out = step(jb, jl)  # compile + warm
            jax.block_until_ready(out)
        except Exception as e:  # pallas unsupported on this backend
            print(f'# {name} path unavailable: {e}', file=sys.stderr)
            continue
        dts = []
        for _ in range(4):
            t0 = time.perf_counter()
            outs = [step(jb, jl) for _ in range(REPEATS)]
            jax.block_until_ready(outs)
            dts.append((time.perf_counter() - t0) / REPEATS)
        mibs = total / min(dts) / (1024 * 1024)
        timed.append((name, mibs, out))

    best = 0.0
    for name, mibs, out in timed:
        # correctness gate, after ALL timing (first readback poisons
        # dispatch): a decode mismatch must fail the benchmark, not
        # skip the path
        assert int(np.asarray(out.n_frames).sum()) == B * FRAMES, \
            f'{name} decode mismatch'
        print(f'# {name} path: {mibs:.2f} MiB/s', file=sys.stderr)
        best = max(best, mibs)
    return best


CLIENTS = 32          # concurrent clients for the runtime bench
GETS_PER_CLIENT = 60  # measured get ops per client


def _percentiles(lat_ms):
    lat_ms = sorted(lat_ms)

    def pct(p):
        return lat_ms[min(len(lat_ms) - 1,
                          int(p / 100.0 * len(lat_ms)))]
    return pct(50), pct(99)


async def _client_ops_run(mode: str) -> dict:
    """One end-to-end runtime measurement: ops/sec and latency
    percentiles for get/set/create plus a watch fan-out, with CLIENTS
    concurrent clients against the in-process server.

    Modes: ``python`` (pure-Python scalar codec, the reference-idiom
    baseline), ``native`` (C++ frame scanner), ``ingest`` (batched
    TPU decode via FleetIngest)."""
    import asyncio

    from zkstream_tpu import Client
    from zkstream_tpu.server import ZKServer

    ingest = None
    use_native = None
    if mode == 'ingest':
        from zkstream_tpu.io.ingest import FleetIngest
        # bypass_bytes=0: this mode exists to measure the batched
        # device pipeline end-to-end; the production small-tick
        # crossover would route this workload through the scalar codec
        # (which the python/native modes already measure).
        ingest = FleetIngest(body_mode='host', max_frames=16,
                             bypass_bytes=0)
    elif mode == 'native':
        use_native = True
    elif mode == 'python':
        use_native = False

    loop = asyncio.get_running_loop()
    srv = await ZKServer().start()
    clients = [Client(address='127.0.0.1', port=srv.port,
                      session_timeout=30000, ingest=ingest,
                      use_native_codec=use_native)
               for _ in range(CLIENTS)]
    for c in clients:
        c.start()
    await asyncio.gather(*[c.wait_connected(timeout=30)
                           for c in clients])
    out = {'mode': mode}
    try:
        await clients[0].create('/b', b'x' * 64)
        if ingest is not None:
            # compile every (batch, length) bucket the workload can
            # touch up front: the bench measures the steady state, and
            # production servers do the same at startup (prewarm docs)
            for nb in (None, 512):
                for bp in (8, 16, CLIENTS):
                    await ingest.prewarm(bp, nb)

        # Warm the path before timing: connection steady state, and —
        # for the ingest — the jit cache across the padded batch-size
        # buckets the tick loop will hit.  Tolerant of a transient
        # disconnect (a client mid-resume raises ZKNotConnectedError;
        # on this single shared core a scheduling blip can trip one).
        from zkstream_tpu.protocol.errors import ZKNotConnectedError

        async def warm(c):
            for _attempt in range(3):
                try:
                    return await c.get('/b')
                except ZKNotConnectedError:
                    await c.wait_connected(timeout=30)
            return await c.get('/b')  # reconnected on the last wait
        for _ in range(5):
            await asyncio.gather(*[warm(c) for c in clients])

        async def timed(coro_fn, n):
            lat = []
            for _ in range(n):
                t0 = loop.time()
                await coro_fn()
                lat.append((loop.time() - t0) * 1000.0)
            return lat

        async def measure(name, coro_of, n_per_client):
            t0 = loop.time()
            lats = await asyncio.gather(*[
                timed(coro_of(c, i), n_per_client)
                for i, c in enumerate(clients)])
            dt = loop.time() - t0
            flat = [x for l in lats for x in l]
            p50, p99 = _percentiles(flat)
            out[name] = {
                'ops_per_sec': round(len(flat) / dt, 1),
                'p50_ms': round(p50, 3), 'p99_ms': round(p99, 3)}

        await measure('get', lambda c, i: lambda: c.get('/b'),
                      GETS_PER_CLIENT)
        await measure('set',
                      lambda c, i: lambda: c.set('/b', b'y' * 64),
                      GETS_PER_CLIENT // 2)
        seqs = [0] * CLIENTS

        def mk_create(c, i):
            async def run():
                seqs[i] += 1
                await c.create('/c%d-%d' % (i, seqs[i]), b'')
            return run
        await measure('create', mk_create, GETS_PER_CLIENT // 4)

        # watch fan-out: every client watches one node; one set fires
        # CLIENTS notifications + re-arm reads through the stack.
        # Arming a dataChanged watch on an existing node emits once
        # immediately (the arming read) — wait those out and reset so
        # the timed window measures only the real notifications.
        fired = []
        armed = loop.create_future()
        done = loop.create_future()

        def on_fire(*a):
            fired.append(1)
            if len(fired) >= CLIENTS:
                if not armed.done():
                    armed.set_result(None)
                elif len(fired) >= CLIENTS and not done.done():
                    done.set_result(None)
        for c in clients:
            c.watcher('/b').on('dataChanged', on_fire)
        await asyncio.wait_for(armed, 10)   # all arm-time emits in
        await asyncio.sleep(0.2)            # all watches re-armed
        fired.clear()
        t0 = loop.time()
        await clients[0].set('/b', b'z' * 64)
        await asyncio.wait_for(done, 10)
        dt = loop.time() - t0
        out['watch_fanout'] = {
            'events': len(fired),
            'events_per_sec': round(len(fired) / dt, 1),
            'total_ms': round(dt * 1000.0, 2)}
        if ingest is not None:
            out['ingest_ticks'] = ingest.ticks
            out['ingest_scalar_ticks'] = ingest.ticks_scalar
            out['ingest_frames'] = ingest.frames_routed
    finally:
        await asyncio.gather(*[c.close() for c in clients])
        await srv.stop()
    return out


def bench_client_ops() -> None:
    """End-to-end runtime numbers (VERDICT r1 items 1/8): the full
    asyncio client stack against the in-process server, per codec
    mode.  Secondary metrics: printed to stderr, one JSON line per
    mode, after the flagship decode numbers are already measured (the
    readbacks here would poison remote-TPU dispatch timing)."""
    import asyncio

    from zkstream_tpu.utils import native

    modes = ['python']
    if native.ensure_lib() is not None:
        modes.append('native')
    modes.append('ingest')
    results = {}
    # Interleaved best-of-2 per mode: this image runs everything on one
    # shared core, so a single sequential pass can swing +-30% on
    # scheduling noise alone.
    for _ in range(2):
        for mode in modes:
            try:
                r = asyncio.run(_client_ops_run(mode))
            except Exception as e:
                # a failed round must not kill the already-printed
                # headline metric; the other round still reports
                print('# client_ops %s round failed: %r' % (mode, e),
                      file=sys.stderr)
                continue
            if (mode not in results
                    or r['get']['ops_per_sec']
                    > results[mode]['get']['ops_per_sec']):
                results[mode] = r
    for mode in modes:
        if mode in results:
            print('# client_ops %s' % json.dumps(results[mode]),
                  file=sys.stderr)
    if not results:
        return
    base = results.get('python', {}).get('get', {}).get('ops_per_sec')
    best_mode = max(results,
                    key=lambda m: results[m]['get']['ops_per_sec'])
    best = results[best_mode]['get']['ops_per_sec']
    print(json.dumps({
        'metric': 'client_get_ops_per_sec',
        'value': best,
        'unit': 'ops/s',
        'vs_baseline': round(best / base, 3) if base else None,
        'mode': best_mode,
    }), file=sys.stderr)


def main() -> None:
    # Initialize the host CPU backend FIRST: the fleet ingest's
    # latency-aware placement wants it, and creating a second PJRT
    # client after heavy accelerator use has been observed to block on
    # a tunneled TPU (the ingest guards with a timeout, but eager init
    # here makes the fast path deterministic).
    try:
        import jax
        jax.devices('cpu')
    except Exception as e:  # pragma: no cover - environment-specific
        print('# cpu backend unavailable: %s' % (e,), file=sys.stderr)

    buf, lens, streams = _fleet()
    scalar = bench_scalar(streams)
    tensor = bench_tensor(buf, lens)
    print(json.dumps({
        'metric': 'wire_decode_throughput',
        'value': round(tensor, 2),
        'unit': 'MiB/s',
        'vs_baseline': round(tensor / scalar, 3),
    }))
    print(f'# scalar baseline: {scalar:.2f} MiB/s over {B} streams x '
          f'{FRAMES} frames', file=sys.stderr)
    try:
        bench_client_ops()
    except Exception as e:  # secondary metrics never sink the run
        print('# client_ops stage failed: %r' % (e,), file=sys.stderr)


if __name__ == '__main__':
    main()
