"""Benchmark: batched wire-decode throughput, TPU data plane vs scalar codec.

The reference publishes no benchmark numbers (BASELINE.md — no
benchmarks/ dir, README is API docs only), so the measurable baseline
is defined here: decode a fleet of framed ZooKeeper reply streams —
frame slicing + reply-header parse + xid routing + max-zxid session
reduction, exactly the per-connection hot path of
lib/zk-streams.js:39-99 / lib/connection-fsm.js:213-229, over a
mixed-opcode corpus (256 B GET_DATA replies, genuine children/ACL
lists, notifications, error and ping replies — deployed-shaped
traffic, not toy frames) — and compare

  baseline:  the scalar bytes-loop codec (zkstream_tpu.protocol), the
             same implementation idiom as the reference's JavaScript
             (per-byte buffer walking on one core), and
  value:     the batched tensor pipeline (zkstream_tpu.ops) on the
             default JAX device (TPU under the driver).

Prints ONE JSON line:
  {"metric": "wire_decode_throughput", "value": <MiB/s>,
   "unit": "MiB/s", "vs_baseline": <tpu/scalar ratio>}
"""

from __future__ import annotations

import json
import os
import struct
import sys
import time

import numpy as np

B = 16384        # streams (connections) per tick
FRAMES = 64      # frames per stream
REPEATS = 30     # dispatches per timing round (x4 rounds, min taken)

# -- mixed-opcode corpus widths (VERDICT r4 next #2: the flagship must
# decode deployed-SHAPED traffic, not 12-byte toy frames) --
DATA_LEN = 256       # GET_DATA payload bytes (fills the 256 B plane)
CH2_N, CH2_NAME = 8, 12      # GET_CHILDREN2: children x name bytes
CH_N, CH_NAME = 6, 10        # GET_CHILDREN (no Stat)
ACL_N, ACL_SCHEME, ACL_ID = 2, 6, 24
NOTIF_PATH = 20

# -- deployed decode-plane widths (io/ingest.py defaults).  One source
# of truth: the full_deployed program, the differential gate, and the
# scalar agreement walks must all use the SAME bounds, or the gates
# would validate against different limits than the timed program --
DEP_DATA, DEP_PATH = 256, 256
DEP_CHILDREN, DEP_NAME = 16, 64
DEP_ACLS, DEP_SCHEME, DEP_ID = 4, 16, 64

#: Per-16-frame opcode pattern, repeated FRAMES/16 times per stream:
#: GET_DATA-dominant (the hot op), with real children/ACL lists, watch
#: notifications, error replies, and ping replies interleaved so every
#: plane of the deployed decode carries live traffic.
_SLOT_PATTERN = (
    'data', 'data', 'children2', 'data', 'notif', 'data', 'acl',
    'data', 'data', 'children', 'data_err', 'data', 'data',
    'children2', 'ping', 'data')

_BODY_LEN = {
    'data': 16 + 4 + DATA_LEN + 68,
    'data_err': 16,                       # error reply: header only
    'children2': 16 + 4 + CH2_N * (4 + CH2_NAME) + 68,
    'children': 16 + 4 + CH_N * (4 + CH_NAME),
    'acl': 16 + 4 + ACL_N * (4 + 4 + ACL_SCHEME + 4 + ACL_ID) + 68,
    'notif': 16 + 4 + 4 + 4 + NOTIF_PATH,
    'ping': 16,
}

_OPCODE = {
    'data': 'GET_DATA', 'data_err': 'GET_DATA',
    'children2': 'GET_CHILDREN2', 'children': 'GET_CHILDREN',
    'acl': 'GET_ACL', 'notif': 'NOTIFICATION', 'ping': 'PING',
}


def _slot_schedule():
    """The corpus's static frame layout: every stream carries the same
    (opcode, width) sequence at the same byte offsets — contents are
    random per stream — so the builder stays vectorized and the gates
    know each frame's ground-truth opcode without re-parsing.  Returns
    (slots, stream_len); each slot is a dict with ``kind``, ``opcode``,
    ``off`` (frame start), ``body_len`` and ``xid_index`` (None for the
    special-xid notification/ping frames)."""
    assert FRAMES % len(_SLOT_PATTERN) == 0
    kinds = _SLOT_PATTERN * (FRAMES // len(_SLOT_PATTERN))
    slots, off, xi = [], 0, 0
    for kind in kinds:
        bl = _BODY_LEN[kind]
        has_xid = kind not in ('notif', 'ping')
        slots.append({'kind': kind, 'opcode': _OPCODE[kind],
                      'off': off, 'body_len': bl,
                      'xid_index': xi if has_xid else None})
        if has_xid:
            xi += 1
        off += 4 + bl
    return slots, off


def _fleet():
    """Vectorized fleet builder: [B, L] framed streams of **valid
    mixed-opcode replies** — reply headers then per-opcode bodies
    (reference layouts: lib/zk-buffer.js:275-370,428-442) per the
    :func:`_slot_schedule` pattern, so the full-decode benchmark
    decodes deployed-shaped traffic: 256 B GET_DATA payloads, genuine
    children and ACL lists, notifications, error and ping replies
    (16384 x ~15.4 KiB = ~247 MiB per tick).

    A shape sweep on the tunneled v5e showed the step time pinned at
    ~90-140 us from 13 MiB up to 208 MiB per tick — the
    remote-dispatch latency floor — so the tick must be fleet-proxy
    sized for the device to be doing meaningful work per dispatch."""
    rng = np.random.RandomState(42)
    slots, L = _slot_schedule()
    v = np.zeros((B, L), np.uint8)

    def be(field, width, out):
        shifts = np.arange(8 * (width - 1), -1, -8, dtype=np.int64)
        out[...] = ((field[..., None] >> shifts) & 0xFF).astype(np.uint8)

    def ri(lo, hi):
        return rng.randint(lo, hi, (B,)).astype(np.int64)

    def full(x):
        return np.full((B,), x, np.int64)

    def ascii_bytes(n):
        return rng.randint(97, 123, (B, n), dtype=np.uint8)  # a-z

    def write_stat(off, mzxid, data_len=0, num_children=0):
        be(ri(1, 1 << 40), 8, v[:, off:off + 8])          # czxid
        be(mzxid, 8, v[:, off + 8:off + 16])              # mzxid
        be(ri(1, 1 << 41), 8, v[:, off + 16:off + 24])    # ctime
        be(ri(1, 1 << 41), 8, v[:, off + 24:off + 32])    # mtime
        be(ri(0, 1 << 10), 4, v[:, off + 32:off + 36])    # version
        be(ri(0, 1 << 10), 4, v[:, off + 36:off + 40])    # cversion
        be(ri(0, 1 << 10), 4, v[:, off + 40:off + 44])    # aversion
        # ephemeralOwner stays 0
        be(full(data_len), 4, v[:, off + 52:off + 56])    # dataLength
        be(full(num_children), 4, v[:, off + 56:off + 60])
        be(ri(1, 1 << 40), 8, v[:, off + 60:off + 68])    # pzxid

    # xids: sequential per stream from a random base, like the
    # connection FSM's allocator — a reply xid is unique in flight
    # (duplicates would poison the pop-on-reply xid map)
    xbase = rng.randint(1, 1 << 19, (B,)).astype(np.int64)

    for s in slots:
        o, kind = s['off'], s['kind']
        be(full(s['body_len']), 4, v[:, o:o + 4])
        if kind == 'notif':
            xid, zxid, err = full(-1), full(-1), 0
        elif kind == 'ping':
            xid, zxid, err = full(-2), ri(1, 1 << 40), 0
        else:
            xid, zxid = xbase + s['xid_index'], ri(1, 1 << 40)
            err = -101 if kind == 'data_err' else 0  # NO_NODE
        be(xid, 4, v[:, o + 4:o + 8])
        be(zxid, 8, v[:, o + 8:o + 16])
        be(full(err), 4, v[:, o + 16:o + 20])
        p = o + 20                                  # payload start
        if kind == 'data':
            be(full(DATA_LEN), 4, v[:, p:p + 4])
            v[:, p + 4:p + 4 + DATA_LEN] = rng.randint(
                0, 256, (B, DATA_LEN), dtype=np.uint8)
            write_stat(p + 4 + DATA_LEN, zxid, data_len=DATA_LEN)
        elif kind in ('children2', 'children'):
            n, w = ((CH2_N, CH2_NAME) if kind == 'children2'
                    else (CH_N, CH_NAME))
            be(full(n), 4, v[:, p:p + 4])
            c = p + 4
            for _k in range(n):
                be(full(w), 4, v[:, c:c + 4])
                v[:, c + 4:c + 4 + w] = ascii_bytes(w)
                c += 4 + w
            if kind == 'children2':
                write_stat(c, zxid, num_children=n)
        elif kind == 'acl':
            be(full(ACL_N), 4, v[:, p:p + 4])
            c = p + 4
            for _k in range(ACL_N):
                be(full(0x1F), 4, v[:, c:c + 4])    # perms: ALL
                be(full(ACL_SCHEME), 4, v[:, c + 4:c + 8])
                v[:, c + 8:c + 8 + ACL_SCHEME] = ascii_bytes(ACL_SCHEME)
                c += 8 + ACL_SCHEME
                be(full(ACL_ID), 4, v[:, c:c + 4])
                v[:, c + 4:c + 4 + ACL_ID] = ascii_bytes(ACL_ID)
                c += 4 + ACL_ID
            write_stat(c, zxid)
        elif kind == 'notif':
            be(ri(1, 5), 4, v[:, p:p + 4])          # type: valid enum
            be(full(3), 4, v[:, p + 4:p + 8])       # SYNC_CONNECTED
            be(full(NOTIF_PATH), 4, v[:, p + 8:p + 12])
            v[:, p + 12] = ord('/')
            v[:, p + 13:p + 12 + NOTIF_PATH] = ascii_bytes(
                NOTIF_PATH - 1)
        # 'ping' / 'data_err': header-only bodies, nothing more
    buf = v
    lens = np.full((B,), L, np.int32)
    streams = [buf[i].tobytes() for i in range(B)]
    return buf, lens, streams, slots


def bench_scalar(streams) -> float:
    """Scalar protocol-tick baseline, MiB/s: length-prefix walk +
    reply-header parse + routing counts + max-zxid per stream —
    exactly the work the device tick metric does (headers only, no
    body materialization, so the comparison is equal-work), as an
    interpreted per-byte loop in the reference's idiom
    (lib/zk-streams.js:39-64 + lib/connection-fsm.js:213-229)."""
    ln_s = struct.Struct('>i')
    hdr = struct.Struct('>iqi')
    total = sum(len(s) for s in streams)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        for s in streams:
            off, n = 0, len(s)
            max_zxid = 0
            n_notif = n_ping = n_err = 0
            while n - off >= 4:
                (ln,) = ln_s.unpack_from(s, off)
                if ln < 0 or ln > 16 << 20 or n - off < 4 + ln:
                    break
                xid, zxid, err = hdr.unpack_from(s, off + 4)
                if xid == -1:
                    n_notif += 1
                elif xid == -2:
                    n_ping += 1
                else:
                    if err:
                        n_err += 1
                    if zxid > max_zxid:
                        max_zxid = zxid
                off += 4 + ln
    dt = time.perf_counter() - t0
    return total * reps / dt / (1024 * 1024)


SCALAR_FULL_STREAMS = 1024   # subset for the interpreted full decode
                             # (throughput is per-byte; ~65k frames is
                             # plenty and keeps the bench under budget)

CHECK_STREAMS = 64           # subset whose scalar packets are retained
                             # frame-for-frame for the differential
                             # device-decode gates


def _xid_maps(sub, slots):
    """Per-stream xid -> opcode maps, as each connection's send side
    would have recorded them (lib/zk-streams.js:145).  Notification and
    ping frames carry reserved xids and never enter the map."""
    hdr_xid = struct.Struct('>i')
    maps = []
    for s in sub:
        m = {}
        for sl in slots:
            if sl['xid_index'] is None:
                continue
            (xid,) = hdr_xid.unpack_from(s, sl['off'] + 4)
            m[xid] = sl['opcode']
        maps.append(m)
    return maps


def bench_scalar_full(streams, slots):
    """Scalar **full decode** baseline, MiB/s: framing + reply header +
    opcode-dispatched body parse into packet dicts (data bytes, child
    lists, ACLs, Stat records) — the complete per-frame receive work of
    the reference client (lib/zk-buffer.js:275-442), interpreted Python
    in the reference's idiom.  Returns (MiB/s, pkts) where ``pkts`` is
    the per-frame packet list of the first CHECK_STREAMS streams — the
    ground truth for the device full-decode differential gates."""
    from zkstream_tpu.protocol.framing import FrameDecoder
    from zkstream_tpu.protocol.jute import JuteReader
    from zkstream_tpu.protocol.records import read_response

    sub = streams[:SCALAR_FULL_STREAMS]
    maps = _xid_maps(sub, slots)
    total = sum(len(s) for s in sub)
    pkts = []
    t0 = time.perf_counter()
    for i, (s, m) in enumerate(zip(sub, maps)):
        dec = FrameDecoder(use_native=False)
        mm = dict(m)
        row = [read_response(JuteReader(body), mm)
               for body in dec.feed(s)]
        if i < CHECK_STREAMS:
            pkts.append(row)
    dt = time.perf_counter() - t0
    return total / dt / (1024 * 1024), pkts


def bench_ext_full(streams, slots) -> float | None:
    """The repo's own C-extension full decode over the same subset —
    context line so the flagship ratio is read against both the
    reference-idiom interpreted loop and this framework's native
    scalar path."""
    from zkstream_tpu.utils import native

    ext = native.ensure_ext()
    if ext is None:
        return None
    from zkstream_tpu.protocol.consts import MAX_PACKET

    sub = streams[:SCALAR_FULL_STREAMS]
    maps = _xid_maps(sub, slots)
    total = sum(len(s) for s in sub)
    t0 = time.perf_counter()
    for s, m in zip(sub, maps):
        pkts, _consumed, kind, _msg = ext.decode_responses(
            s, dict(m), MAX_PACKET)
        assert kind is None and len(pkts) == FRAMES
    dt = time.perf_counter() - t0
    return total / dt / (1024 * 1024)


#: Device-OOM signatures worth a serialized retry.  Deliberately a
#: tight allowlist (XLA's RESOURCE_EXHAUSTED status, the literal
#: "out of memory" phrasing, an OOM token): the old bare
#: ``'memory' in str(e)`` substring also matched deterministic
#: failures that merely *mentioned* memory (e.g. layout/"memory
#: space" errors), and re-running heavy dispatches behind one of
#: those wastes a scarce tunnel window.
_OOM_SIGNATURES = ('RESOURCE_EXHAUSTED', 'OOM')


def _is_oom(e: BaseException) -> bool:
    msg = str(e)
    # The all-caps tokens must match case-sensitively: lowercasing
    # 'OOM' would turn it into a bare 'oom' substring and re-admit
    # false positives ('zoomed', 'Bloom').
    return (any(sig in msg for sig in _OOM_SIGNATURES)
            or 'out of memory' in msg.lower())


def bench_tensor(buf, lens, streams, pkts, slots
                 ) -> tuple[float, float, float]:
    """Tensor pipeline MiB/s on the default JAX device: the protocol
    tick (header decode + routing) and the **full decode** (tick +
    batched reply-body parse, ops/replies.py — the work of
    lib/zk-buffer.js:275-442).  Returns (tick_mibs, full_mibs).

    The tick times the fused Pallas kernel (ops/pallas_scan.py) and
    the pure-jnp pipeline (whose XLA scan gathers only header bytes —
    the usual winner on TPU; also the fallback where Pallas cannot
    lower, e.g. plain CPU jax) and reports the best; both are
    property-tested equivalent (tests/test_pallas.py).

    All timing runs BEFORE any device->host readback: on a tunneled
    remote TPU, the first readback of a computation output permanently
    flips the client into per-dispatch synchronization (~60x slower
    dispatches for the rest of the process), so the correctness gates
    — including the full-decode equality check against the scalar
    codec's packet — run after every candidate has been timed."""
    import jax
    import jax.numpy as jnp

    from zkstream_tpu.ops.pipeline import (
        wire_pipeline_step,
        wire_pipeline_step_pallas,
    )
    from zkstream_tpu.ops.replies import (
        parse_list_bodies,
        parse_reply_bodies,
    )

    jb, jl = jnp.asarray(buf), jnp.asarray(lens)

    def full(b, l):
        st = wire_pipeline_step(b, l, max_frames=FRAMES)
        bd = parse_reply_bodies(b, st.starts, st.sizes,
                                max_data=16, max_path=8)
        return st, bd

    def full_deployed(b, l):
        # the configuration the SHIPPED ingest runs (io/ingest.py
        # defaults): 256-byte data/path planes plus the speculative
        # children/ACL list planes — every layout parsed at every
        # frame, exactly the deployed device-bodies work
        st = wire_pipeline_step(b, l, max_frames=FRAMES)
        bd = parse_reply_bodies(b, st.starts, st.sizes,
                                max_data=DEP_DATA, max_path=DEP_PATH)
        lb = parse_list_bodies(b, st.starts, st.sizes,
                               max_children=DEP_CHILDREN,
                               max_name=DEP_NAME, max_acls=DEP_ACLS,
                               max_scheme=DEP_SCHEME, max_id=DEP_ID)
        return st, bd, lb

    # the CPU-fallback backend is ~3 orders slower than the chip per
    # byte; fewer repeats keep a wedged-tunnel run inside the budget
    # without changing what is measured (min-of-rounds either way)
    reps = REPEATS if jax.default_backend() != 'cpu' \
        else max(6, REPEATS // 3)
    candidates = [
        ('pallas', lambda b, l: wire_pipeline_step_pallas(
            b, l, max_frames=FRAMES, block_rows=64), reps, None),
        ('jnp', lambda b, l: wire_pipeline_step(
            b, l, max_frames=FRAMES), reps, None),
        ('full', full, reps, None),
        # deployed widths cost ~20x the toy planes in output bytes
        # (ONE output is ~2.2 GiB: 256 B data + 256 B path + 16x64
        # children names + ACL planes per slot, over 16384x64 slots);
        # fewer repeats AND a 2-deep dispatch cap keep peak HBM under
        # ~5 GiB so the flagship cannot RESOURCE_EXHAUSTED a 16 GB
        # chip mid-run — the r4 lesson, OOM edition: the benchmark
        # completing beats a few % of pipelining
        ('full-deployed', full_deployed, max(4, reps // 5), 2),
    ]
    total = int(lens.sum())
    timed = []
    for name, fn, reps, inflight in candidates:
        try:
            step = jax.jit(fn)
            out = step(jb, jl)  # compile + warm
            jax.block_until_ready(out)
        except Exception as e:  # pallas unsupported on this backend
            print(f'# {name} path unavailable: {e}', file=sys.stderr)
            continue
        def leaf(o):
            # keep only one tiny output leaf per repeat: it becomes
            # ready when the whole computation does (valid timing),
            # while the big body planes free as dispatches retire —
            # holding REPEATS full-decode outputs (0.5-4 GiB each)
            # exhausts device memory
            # WireStats (namedtuple) or a (st, bodies...) tuple
            return (o.n_frames if hasattr(o, 'n_frames')
                    else o[0].n_frames)

        def time_rounds(cap, rounds=4):
            dts = []
            for _ in range(rounds):
                t0 = time.perf_counter()
                done = 0
                while done < reps:
                    k = min(cap, reps - done)
                    outs = [leaf(step(jb, jl)) for _ in range(k)]
                    jax.block_until_ready(outs)
                    done += k
                dts.append((time.perf_counter() - t0) / reps)
            return dts

        try:
            dts = time_rounds(inflight or reps)
        except Exception as e:
            oom = _is_oom(e)
            if inflight is None or inflight <= 1 or not oom:
                raise
            # a device OOM mid-timing (big planes, small chip) must
            # not kill the flagship: serialize dispatches and retry.
            # Only OOM-shaped errors qualify — anything else is
            # deterministic and re-running heavy dispatches behind a
            # misleading message would waste a scarce tunnel window
            print(f'# {name}: timing at inflight={inflight} hit '
                  f'device OOM ({e!r}); retrying serialized',
                  file=sys.stderr)
            dts = time_rounds(1)
        mibs = total / min(dts) / (1024 * 1024)
        timed.append((name, mibs, out))

    tick_best = full_best = full_deployed_best = 0.0
    for name, mibs, out in timed:
        # correctness gates, after ALL timing (first readback poisons
        # dispatch): a decode mismatch must fail the benchmark, not
        # skip the path
        if name == 'full':
            st, bd = out
            _gate_planes(st, bd, None, slots)
            _gate_differential(st, bd, None, pkts, slots,
                               max_data=16, max_path=8)
            full_best = mibs
        elif name == 'full-deployed':
            st, bd, lb = out
            _gate_planes(st, bd, lb, slots)
            _gate_differential(st, bd, lb, pkts, slots,
                               max_data=DEP_DATA, max_path=DEP_PATH)
            _gate_list_agreement(lb, streams, slots)
            full_deployed_best = mibs
        else:
            assert int(np.asarray(out.n_frames).sum()) == B * FRAMES, \
                f'{name} decode mismatch'
            tick_best = max(tick_best, mibs)
        print(f'# {name} path: {mibs:.2f} MiB/s', file=sys.stderr)
    # the skip-on-exception escape is for the OPTIONAL pallas path;
    # the mandatory paths must have timed, else the run reports a
    # zero flagship instead of failing
    assert tick_best > 0, 'no tick path timed'
    assert full_best > 0, 'full-decode path never timed'
    assert full_deployed_best > 0, 'deployed-width path never timed'
    return tick_best, full_best, full_deployed_best


def _host_planes(planes, n):
    """First-``n``-streams host copy of a NamedTuple of [B, F, ...]
    device planes (slice on device first: the full body planes are
    GiB-scale and only the checked subset needs to come back)."""
    return type(planes)(*[
        _host_planes(x, n) if hasattr(x, '_fields')
        else np.asarray(x[:n]) for x in planes])


def _gate_planes(st, bd, lb, slots) -> None:
    """Plane-wide cheap gates over ALL streams: every frame found, and
    every slot's [B, F] summary planes uniform at the corpus's known
    per-slot ground truth (the per-byte field comparison happens on the
    checked subset in :func:`_gate_differential`)."""
    assert int(np.asarray(st.n_frames).sum()) == B * FRAMES, \
        'full decode lost frames'
    data_len = np.asarray(bd.data_len)
    data_ok = np.asarray(bd.data_ok)
    sad_valid = np.asarray(bd.stat_after_data.valid)
    for f, sl in enumerate(slots):
        if sl['kind'] == 'data':
            assert data_ok[:, f].all(), f'data_ok hole at slot {f}'
            assert (data_len[:, f] == DATA_LEN).all(), \
                f'data_len mismatch at slot {f}'
            assert sad_valid[:, f].all(), f'Stat hole at slot {f}'
    if lb is None:
        return
    ch_ok = np.asarray(lb.ch_ok)
    ch_count = np.asarray(lb.ch_count)
    sac_valid = np.asarray(lb.stat_after_children.valid)
    acl_ok = np.asarray(lb.acl_ok)
    acl_count = np.asarray(lb.acl_count)
    saa_valid = np.asarray(lb.stat_after_acl.valid)
    for f, sl in enumerate(slots):
        if sl['kind'] in ('children', 'children2'):
            n = CH2_N if sl['kind'] == 'children2' else CH_N
            assert ch_ok[:, f].all(), f'ch_ok hole at slot {f}'
            assert (ch_count[:, f] == n).all(), \
                f'ch_count mismatch at slot {f}'
            if sl['kind'] == 'children2':
                assert sac_valid[:, f].all(), \
                    f'children2 Stat hole at slot {f}'
        elif sl['kind'] == 'acl':
            assert acl_ok[:, f].all(), f'acl_ok hole at slot {f}'
            assert (acl_count[:, f] == ACL_N).all(), \
                f'acl_count mismatch at slot {f}'
            assert saa_valid[:, f].all(), f'ACL Stat hole at slot {f}'


def _gate_differential(st, bd, lb, pkts, slots, max_data: int,
                       max_path: int) -> None:
    """The differential gate (VERDICT r4 next #1): every frame of the
    checked subset must decode field-for-field to what the scalar codec
    (``records.read_response``) produced from the same bytes — headers,
    payload bytes (up to the plane width, with the true length reported
    either way), child lists, ACLs, notification fields, and Stats."""
    from zkstream_tpu.ops.replies import stat_from_planes
    from zkstream_tpu.protocol.consts import (
        KeeperState,
        NotificationType,
    )

    C = len(pkts)
    xids = np.asarray(st.xids[:C])
    errs = np.asarray(st.errs[:C])
    b = _host_planes(bd, C)
    lw = _host_planes(lb, C) if lb is not None else None
    for i, row in enumerate(pkts):
        assert len(row) == FRAMES
        for f, pkt in enumerate(row):
            sl = slots[f]
            op = pkt['opcode']
            assert op == sl['opcode'], (i, f, op)
            assert int(xids[i, f]) == pkt['xid'], (i, f)
            if pkt['err'] != 'OK':
                assert sl['kind'] == 'data_err' and int(errs[i, f]) != 0
                continue
            assert int(errs[i, f]) == 0, (i, f)
            if op == 'GET_DATA':
                n = len(pkt['data'])
                assert bool(b.data_ok[i, f])
                assert int(b.data_len[i, f]) == n
                k = min(n, max_data)
                assert bytes(b.data[i, f, :k]) == pkt['data'][:k]
                assert bool(b.stat_after_data.valid[i, f])
                assert stat_from_planes(b.stat_after_data, i, f) \
                    == pkt['stat'], (i, f)
            elif op == 'NOTIFICATION':
                assert NotificationType(int(b.ntype[i, f])).name \
                    == pkt['type']
                assert KeeperState(int(b.nstate[i, f])).name \
                    == pkt['state']
                path = pkt['path'].encode()
                assert bool(b.npath_ok[i, f])
                assert int(b.npath_len[i, f]) == len(path)
                k = min(len(path), max_path)
                assert bytes(b.npath[i, f, :k]) == path[:k]
            elif op in ('GET_CHILDREN', 'GET_CHILDREN2'):
                if lw is None:
                    continue                 # toy run: no list planes
                assert bool(lw.ch_ok[i, f]), (i, f)
                cnt = int(lw.ch_count[i, f])
                assert cnt == len(pkt['children'])
                got = [bytes(lw.ch_bytes[i, f, k,
                                         :int(lw.ch_len[i, f, k])]
                             ).decode() for k in range(cnt)]
                assert got == pkt['children'], (i, f)
                if op == 'GET_CHILDREN2':
                    assert bool(lw.stat_after_children.valid[i, f])
                    assert stat_from_planes(
                        lw.stat_after_children, i, f) == pkt['stat']
            elif op == 'GET_ACL':
                if lw is None:
                    continue
                assert bool(lw.acl_ok[i, f]), (i, f)
                cnt = int(lw.acl_count[i, f])
                assert cnt == len(pkt['acl'])
                for k in range(cnt):
                    want = pkt['acl'][k]
                    assert int(lw.acl_perms[i, f, k]) == int(want.perms)
                    assert bytes(lw.acl_scheme[
                        i, f, k, :int(lw.acl_scheme_len[i, f, k])]
                        ).decode() == want.id.scheme
                    assert bytes(lw.acl_id[
                        i, f, k, :int(lw.acl_id_len[i, f, k])]
                        ).decode() == want.id.id
                assert bool(lw.stat_after_acl.valid[i, f])
                assert stat_from_planes(lw.stat_after_acl, i, f) \
                    == pkt['stat']
            elif op == 'PING':
                pass
            else:
                raise AssertionError('unexpected opcode %r' % (op,))


def _scalar_children_walk(body: bytes, max_children: int,
                          max_name: int):
    """The scalar codec's speculative children-list read, mirroring
    exactly what the device plane promises to accept: a leading count
    within the static bound, then count jute buffers, each fitting the
    frame (negative length decodes as empty — the jute.py:182-183
    quirk) and no longer than the name plane.  Returns the element
    list, or None where the walk rejects."""
    from zkstream_tpu.protocol.jute import JuteReader

    r = JuteReader(body[16:])
    try:
        count = r.read_int()
        if count < 0 or count > max_children:
            return None
        out = []
        for _ in range(count):
            e = r.read_buffer()
            if len(e) > max_name:
                return None
            out.append(e)
        return out
    except Exception:
        return None


def _scalar_acl_walk(body: bytes, max_acls: int, max_scheme: int,
                     max_id: int):
    """Speculative ACL-list read with the device plane's bounds; see
    :func:`_scalar_children_walk`."""
    from zkstream_tpu.protocol.jute import JuteReader

    r = JuteReader(body[16:])
    try:
        count = r.read_int()
        if count < 0 or count > max_acls:
            return None
        out = []
        for _ in range(count):
            perms = r.read_int()
            scheme = r.read_buffer()
            ident = r.read_buffer()
            if len(scheme) > max_scheme or len(ident) > max_id:
                return None
            out.append((perms, scheme, ident))
        return out
    except Exception:
        return None


def _gate_list_agreement(lb, streams, slots) -> None:
    """The r4 failure's replacement (VERDICT r4 next #1): the list
    planes' ok masks must agree with the scalar codec's speculative
    read over the same bytes — INCLUDING coincidental accepts, where a
    random GET_DATA payload legitimately parses as a list under the
    negative-length=>empty quirk (~tens per million random frames; the
    r4 gate wrongly asserted zero and could never pass).  Checked over
    the scalar-subset streams: device-accept => scalar-accept with the
    same element count, and scalar ground truth (the corpus's genuine
    list slots) => device-accept, verified plane-wide in
    :func:`_gate_planes`."""
    C = min(SCALAR_FULL_STREAMS, len(streams))
    ch_ok = np.asarray(lb.ch_ok[:C])
    ch_count = np.asarray(lb.ch_count[:C])
    acl_ok = np.asarray(lb.acl_ok[:C])
    acl_count = np.asarray(lb.acl_count[:C])
    n_coincident = 0
    for i in range(C):
        s = streams[i]
        for f in np.nonzero(ch_ok[i])[0]:
            sl = slots[f]
            body = s[sl['off'] + 4:sl['off'] + 4 + sl['body_len']]
            walk = _scalar_children_walk(body, DEP_CHILDREN, DEP_NAME)
            assert walk is not None, \
                ('device ch_ok but scalar walk rejects', i, int(f))
            assert len(walk) == int(ch_count[i, f]), (i, int(f))
            if sl['kind'] not in ('children', 'children2'):
                n_coincident += 1
        for f in np.nonzero(acl_ok[i])[0]:
            sl = slots[f]
            body = s[sl['off'] + 4:sl['off'] + 4 + sl['body_len']]
            walk = _scalar_acl_walk(body, DEP_ACLS, DEP_SCHEME, DEP_ID)
            assert walk is not None, \
                ('device acl_ok but scalar walk rejects', i, int(f))
            assert len(walk) == int(acl_count[i, f]), (i, int(f))
    print('# list-plane agreement: %d coincidental accepts over %d '
          'frames, all scalar-confirmed' % (n_coincident, C * FRAMES),
          file=sys.stderr)


CLIENT_SCALES = (32, 128)  # fleet sizes for the runtime bench (the
                           # crossover sweep, CROSSOVER.md, shows the
                           # batched path winning from ~128 conns)
OPS_TOTAL = 1920           # measured ops per workload, fleet-wide


def _percentiles(lat_ms):
    lat_ms = sorted(lat_ms)

    def pct(p):
        return lat_ms[min(len(lat_ms) - 1,
                          int(p / 100.0 * len(lat_ms)))]
    return pct(50), pct(99)


async def _client_ops_run(mode: str, n_clients: int,
                          write_heavy: bool = False,
                          wal: str | None = None) -> dict:
    """One end-to-end runtime measurement: ops/sec and latency
    percentiles for get/set/create plus a watch fan-out, with
    ``n_clients`` concurrent clients against the in-process server.

    Modes: ``python`` (pure-Python scalar codec, the reference-idiom
    baseline), ``native`` (C++ frame scanner), ``ingest`` (batched
    TPU decode via FleetIngest).  ``write_heavy`` flips the op mix to
    SET_DATA/CREATE-dominated (the outbound-plane cell family, `make
    bench-write`); every cell also scrapes the flush-batch-size
    histograms (io/sendplane.py) from both planes.  ``wal`` attaches
    the durability plane (server/persist.py) at that fsync policy
    ('tick' | 'always' | 'never'; None = off — the `make bench-wal`
    paired family) and scrapes its fsync-latency histogram into the
    cell."""
    import asyncio
    import shutil
    import tempfile

    from zkstream_tpu import Client
    from zkstream_tpu.io.sendplane import scrape_flush_cells
    from zkstream_tpu.server import ZKServer

    ingest = None
    use_native = None
    if mode == 'ingest':
        from zkstream_tpu.io.ingest import FleetIngest
        # bypass_bytes=0: this mode exists to measure the batched
        # device pipeline end-to-end; the production small-tick
        # crossover would route this workload through the scalar codec
        # (which the python/native modes already measure).  max_frames
        # fleet-sized per CROSSOVER.md (oversized per-stream slots are
        # padding waste at fleet scale).
        ingest = FleetIngest(body_mode='host', max_frames=8,
                             bypass_bytes=0)
    elif mode == 'native':
        use_native = True
    elif mode == 'python':
        use_native = False

    loop = asyncio.get_running_loop()
    # one shared collector: every client's per-op latency lands in the
    # same zookeeper_op_latency_ms histogram, scraped into the result
    # below so BENCH_*.json carries histogram-derived p50/p99 per op
    # next to the workload-timed percentiles; the server shares it so
    # both planes' flush-batch histograms land in the same scrape
    from zkstream_tpu.utils.metrics import Collector
    collector = Collector()
    # WAL cells default to tmpfs (/dev/shm) when available: the paired
    # family isolates the durability PLANE's cost (encode + CRC32C +
    # group-commit machinery + ack gating) from the ambient device —
    # this image's 9p filesystem syncs at ~0.6 ms, an artifact of the
    # container, not of the design.  Point ZKSTREAM_BENCH_WAL_DIR at a
    # real data dir to measure a device-bound envelope instead; either
    # way the cell's fsync-latency histogram says which device it saw.
    wal_dir = None
    db = None
    if wal:
        base = os.environ.get('ZKSTREAM_BENCH_WAL_DIR') or (
            '/dev/shm' if os.path.isdir('/dev/shm') else None)
        wal_dir = tempfile.mkdtemp(prefix='zkbench-wal-', dir=base)
    else:
        # the off/baseline arm must stay WAL-free even when the
        # ambient ZKSTREAM_WAL_DIR default is set — an explicit db
        # skips the server's env resolution (and a shared ambient dir
        # would leak state between rounds on top of it)
        from zkstream_tpu.server import ZKDatabase
        db = ZKDatabase()
    srv = await ZKServer(db=db, collector=collector, wal_dir=wal_dir,
                         durability=wal).start()
    clients = [Client(address='127.0.0.1', port=srv.port,
                      session_timeout=30000, ingest=ingest,
                      use_native_codec=use_native,
                      collector=collector)
               for _ in range(n_clients)]
    for c in clients:
        c.start()
    await asyncio.gather(*[c.wait_connected(timeout=30)
                           for c in clients])
    out = {'mode': mode, 'conns': n_clients,
           'workload': 'write' if write_heavy else 'mixed'}
    if wal:
        out['wal'] = wal
    try:
        await clients[0].create('/b', b'x' * 64)
        if ingest is not None:
            # compile every (batch, length) bucket the workload can
            # touch up front: the bench measures the steady state, and
            # production servers do the same at startup (prewarm docs)
            bp = 8
            while bp <= n_clients:
                for nb in (None, 512):
                    await ingest.prewarm(bp, nb)
                bp *= 2

        # Warm the path before timing: connection steady state, and —
        # for the ingest — the jit cache across the padded batch-size
        # buckets the tick loop will hit.  Tolerant of a transient
        # disconnect (a client mid-resume raises ZKNotConnectedError;
        # on this single shared core a scheduling blip can trip one).
        from zkstream_tpu.protocol.errors import ZKNotConnectedError

        async def warm(c):
            for _attempt in range(3):
                try:
                    return await c.get('/b')
                except ZKNotConnectedError:
                    await c.wait_connected(timeout=30)
            return await c.get('/b')  # reconnected on the last wait
        for _ in range(5):
            await asyncio.gather(*[warm(c) for c in clients])

        async def timed(coro_fn, n):
            lat = []
            for _ in range(n):
                t0 = loop.time()
                await coro_fn()
                lat.append((loop.time() - t0) * 1000.0)
            return lat

        async def measure(name, coro_of, n_per_client):
            t0 = loop.time()
            lats = await asyncio.gather(*[
                timed(coro_of(c, i), n_per_client)
                for i, c in enumerate(clients)])
            dt = loop.time() - t0
            flat = [x for l in lats for x in l]
            p50, p99 = _percentiles(flat)
            out[name] = {
                'ops_per_sec': round(len(flat) / dt, 1),
                'p50_ms': round(p50, 3), 'p99_ms': round(p99, 3)}

        per = max(8, OPS_TOTAL // n_clients)
        seqs = [0] * n_clients

        def mk_create(c, i):
            async def run():
                seqs[i] += 1
                await c.create('/c%d-%d' % (i, seqs[i]), b'')
            return run
        if write_heavy:
            # SET_DATA/CREATE-dominated: the outbound plane's shape
            await measure('set',
                          lambda c, i: lambda: c.set('/b', b'y' * 64),
                          per)
            await measure('create', mk_create, per // 2)
            await measure('get', lambda c, i: lambda: c.get('/b'),
                          per // 4)
        else:
            await measure('get', lambda c, i: lambda: c.get('/b'),
                          per)
            await measure('set',
                          lambda c, i: lambda: c.set('/b', b'y' * 64),
                          per // 2)
            await measure('create', mk_create, per // 4)

        # watch fan-out: every client watches one node; one set fires
        # n_clients notifications + re-arm reads through the stack.
        # Arming a dataChanged watch on an existing node emits once
        # immediately (the arming read) — wait those out and reset so
        # the timed window measures only the real notifications.
        fired = []
        armed = loop.create_future()
        done = loop.create_future()

        def on_fire(*a):
            fired.append(1)
            if len(fired) >= n_clients:
                if not armed.done():
                    armed.set_result(None)
                elif len(fired) >= n_clients and not done.done():
                    done.set_result(None)
        for c in clients:
            c.watcher('/b').on('dataChanged', on_fire)
        await asyncio.wait_for(armed, 10)   # all arm-time emits in
        await asyncio.sleep(0.2)            # all watches re-armed
        fired.clear()
        t0 = loop.time()
        await clients[0].set('/b', b'z' * 64)
        await asyncio.wait_for(done, 10)
        dt = loop.time() - t0
        out['watch_fanout'] = {
            'events': len(fired),
            'events_per_sec': round(len(fired) / dt, 1),
            'total_ms': round(dt * 1000.0, 2)}
        if ingest is not None:
            out['ingest_ticks'] = ingest.ticks
            out['ingest_scalar_ticks'] = ingest.ticks_scalar
            # nonzero = a bucket miss sent timed ops through the
            # scalar drain while its program compiled; published so
            # 'ingest'-labeled numbers are honest about it
            out['ingest_warming_ticks'] = ingest.ticks_warming
            out['ingest_frames'] = ingest.frames_routed

        # Per-op latency distribution from the production histogram
        # (zookeeper_op_latency_ms, every completion path, warm-up
        # and watch re-arm reads included): the same series a scrape
        # of a live deployment shows, published alongside the
        # workload-timed percentiles above so the two views are
        # cross-checkable in BENCH_*.json.
        hist = collector.get_collector('zookeeper_op_latency_ms')
        ops_hist = {}
        for key in hist.label_keys():
            labels = dict(key)
            opname = labels.get('op', '')
            n = hist.count(labels)
            if not n:
                continue
            ops_hist[opname.lower()] = {
                'count': n,
                'p50_ms': round(hist.percentile(50, labels), 3),
                'p99_ms': round(hist.percentile(99, labels), 3),
            }
        out['op_latency_hist'] = ops_hist
        # Flush-batch-size distributions (io/sendplane.py), both
        # planes — the coalescing observability the write-heavy cells
        # exist to publish.
        out['flush_batches'] = scrape_flush_cells(collector)
        # The tick ledger (utils/metrics.TickLedger): what fraction of
        # each busy loop tick the decode/fsync/cork/fan-out planes
        # ate — the per-cell phase table PROFILE.md's accept-shard and
        # io_uring items are gated on.
        from zkstream_tpu.utils.metrics import scrape_tick_cells
        if srv.ledger is not None:
            srv.ledger.close_tick()   # flush the residual burst
        tick = scrape_tick_cells(collector)
        if tick:
            out['tick_ledger'] = tick
        if wal:
            from zkstream_tpu.server.persist import scrape_wal_cells
            out['wal_stats'] = scrape_wal_cells(collector)
            out['wal_stats']['sync_errors'] = srv.db.wal.sync_errors
    finally:
        await asyncio.gather(*[c.close() for c in clients])
        await srv.stop()
        if srv.db.wal is not None:
            srv.db.wal.close()
        if wal_dir is not None:
            shutil.rmtree(wal_dir, ignore_errors=True)
    return out


def bench_client_ops(write_heavy: bool = False) -> None:
    """End-to-end runtime numbers (VERDICT r1 items 1/8): the full
    asyncio client stack against the in-process server, per codec
    mode.  Secondary metrics: printed to stderr, one JSON line per
    mode, after the flagship decode numbers are already measured (the
    readbacks here would poison remote-TPU dispatch timing).

    ``write_heavy`` runs the SET_DATA/CREATE-dominated cell family
    instead (`make bench-write`); the headline op becomes ``set``."""
    import asyncio

    from zkstream_tpu.utils import native

    headline = 'set' if write_heavy else 'get'
    modes = ['python']
    if native.ensure_lib() is not None:
        modes.append('native')
    modes.append('ingest')
    results: dict = {}
    # Interleaved best-of-2 per cell: this image runs everything on one
    # shared core, so a single sequential pass can swing +-30% on
    # scheduling noise alone.
    for _ in range(2):
        for n in CLIENT_SCALES:
            for mode in modes:
                try:
                    r = asyncio.run(_client_ops_run(
                        mode, n, write_heavy=write_heavy))
                except Exception as e:
                    # a failed round must not kill the already-printed
                    # headline metric; the other round still reports
                    print('# client_ops %s@%d round failed: %r'
                          % (mode, n, e), file=sys.stderr)
                    continue
                key = (mode, n)
                if (key not in results
                        or r[headline]['ops_per_sec']
                        > results[key][headline]['ops_per_sec']):
                    results[key] = r
    for n in CLIENT_SCALES:
        for mode in modes:
            if (mode, n) in results:
                print('# client_ops %s'
                      % json.dumps(results[(mode, n)]), file=sys.stderr)
    for n in CLIENT_SCALES:
        cell = {m: results[(m, n)] for m in modes if (m, n) in results}
        if not cell:
            continue
        base = cell.get('python', {}).get(headline,
                                          {}).get('ops_per_sec')
        best_mode = max(
            cell, key=lambda m: cell[m][headline]['ops_per_sec'])
        best = cell[best_mode][headline]['ops_per_sec']
        print(json.dumps({
            'metric': 'client_%s_ops_per_sec' % (headline,),
            'conns': n,
            'value': best,
            'unit': 'ops/s',
            'vs_baseline': round(best / base, 3) if base else None,
            'mode': best_mode,
        }), file=sys.stderr)


#: `bench.py --wal` fleet sizes (the acceptance envelope: sync=tick
#: must not be significantly slower than wal-off at 16 and 64).
WAL_SCALES = (16, 64)
WAL_ARMS = (None, 'tick', 'always')


def bench_wal() -> None:
    """The durability plane's cost envelope (`make bench-wal`):
    paired write-heavy cells — wal-off vs sync=tick (group commit:
    one fsync per tick, riding the send-plane cork) vs sync=always
    (one fsync per txn) — at fleet 16/64, with the fsync-latency
    histogram scraped into every wal cell.  Per-round adjacent A/B/C
    runs, sign of the per-round headline (set ops/s) delta, exact
    two-sided sign test; the measured table lives in PROFILE.md
    "Durability plane"."""
    import asyncio

    from zkstream_tpu.utils import native
    from zkstream_tpu.utils.metrics import sign_test_p

    mode = 'native' if native.ensure_lib() is not None else 'python'
    rounds = int(os.environ.get('ZKSTREAM_BENCH_WAL_ROUNDS', '10'))
    # rows[(conns, arm)] -> list of per-round set-ops/s
    rows: dict = {}
    cells: dict = {}
    for rnd in range(rounds):
        for n in WAL_SCALES:
            for arm in WAL_ARMS:
                try:
                    r = asyncio.run(_client_ops_run(
                        mode, n, write_heavy=True, wal=arm))
                except Exception as e:
                    print('# wal cell %s@%d round failed: %r'
                          % (arm or 'off', n, e), file=sys.stderr)
                    continue
                key = (n, arm or 'off')
                rows.setdefault(key, []).append(
                    r['set']['ops_per_sec'])
                if key not in cells or r['set']['ops_per_sec'] > \
                        cells[key]['set']['ops_per_sec']:
                    cells[key] = r
    for key in sorted(cells, key=str):
        print('# wal_cell %s' % json.dumps(cells[key]),
              file=sys.stderr)
    for n in WAL_SCALES:
        for a_arm, b_arm, label in (
                ('tick', 'off', 'tick-vs-off'),
                ('always', 'tick', 'always-vs-tick'),
                ('always', 'off', 'always-vs-off')):
            a = rows.get((n, a_arm), [])
            b = rows.get((n, b_arm), [])
            if not a or not b:
                continue
            paired = list(zip(a, b))
            deltas = [(x - y) / y * 100.0 for x, y in paired if y]
            wins = sum(1 for x, y in paired if x > y)
            losses = sum(1 for x, y in paired if x < y)
            print(json.dumps({
                'metric': 'wal_group_commit_sign_test',
                'pair': label,
                'conns': n,
                'rounds': len(paired),
                'wins': wins,
                'losses': losses,
                'mean_delta_pct': round(sum(deltas)
                                        / max(1, len(deltas)), 1),
                'sign_p': round(sign_test_p(wins, losses), 4),
            }), flush=True)


#: `bench.py --election` ensemble sizes: does failover time move with
#: membership (more voters, same one-round tally)?
ELECTION_SCALES = (3, 5)


async def _election_round(members: int, heartbeat_ms: int = 40
                          ) -> dict:
    """One failover measurement: fresh in-process ensemble + client,
    kill the leader, time (a) the election itself (zk_election_ms —
    detection to promotion inside the coordinator) and (b) the
    client-observed failover (kill to the first acked write through
    the elected successor)."""
    import asyncio as aio
    import time as _t

    from zkstream_tpu import Client
    from zkstream_tpu.protocol.errors import ZKError, ZKProtocolError
    from zkstream_tpu.server import ZKEnsemble
    from zkstream_tpu.server.election import METRIC_ELECTION
    from zkstream_tpu.utils.metrics import Collector

    collector = Collector()
    ens = await ZKEnsemble(members, heartbeat_ms=heartbeat_ms,
                           seed=members, collector=collector).start()
    c = Client(servers=ens.addresses(), shuffle_backends=False,
               session_timeout=8000)
    c.start()
    try:
        await c.wait_connected(timeout=10)
        await c.create('/warm', b'w')
        elected = aio.get_running_loop().create_future()
        ens.election.on(
            'elected',
            lambda m, e, d: (not elected.done()
                             and elected.set_result(d)))
        t0 = _t.perf_counter()
        await ens.kill(0)
        election_ms = await aio.wait_for(elected, 15)
        # client-observed: first acked write through the successor
        while True:
            try:
                await c.set('/warm', b'x', version=-1)
                break
            except (ZKError, ZKProtocolError):
                await aio.sleep(0.01)
        failover_ms = (_t.perf_counter() - t0) * 1000.0
        hist = collector.get_collector(METRIC_ELECTION)
        return {'members': members,
                'election_ms': round(election_ms, 3),
                'election_p50_ms': round(hist.percentile(50), 3),
                'failover_ms': round(failover_ms, 3)}
    finally:
        await c.close()
        await ens.stop()


def bench_election() -> None:
    """The coordination plane's failover envelope (`make
    bench-election`): paired leader-kill cells at 3- vs 5-member
    ensembles — per-round adjacent A/B runs, exact two-sided sign
    test on the client-observed failover time, zk_election_ms
    distribution per cell.  Rounds via
    ZKSTREAM_BENCH_ELECTION_ROUNDS."""
    import asyncio

    from zkstream_tpu.utils.metrics import sign_test_p

    rounds = int(os.environ.get('ZKSTREAM_BENCH_ELECTION_ROUNDS',
                                '10'))
    rows: dict = {n: [] for n in ELECTION_SCALES}
    cells: dict = {}
    paired_rounds: list = []
    for _rnd in range(rounds):
        this_round: dict = {}
        for n in ELECTION_SCALES:
            try:
                r = asyncio.run(_election_round(n))
            except Exception as e:
                print('# election cell members=%d round failed: %r'
                      % (n, e), file=sys.stderr)
                continue
            rows[n].append(r['failover_ms'])
            this_round[n] = r['failover_ms']
            if n not in cells or r['failover_ms'] \
                    < cells[n]['failover_ms']:
                cells[n] = r
        if len(this_round) == len(ELECTION_SCALES):
            # only rounds where EVERY arm completed pair up — a
            # failed cell must not shift later rounds against
            # earlier ones (the adjacent-pairing contract)
            paired_rounds.append(tuple(this_round[n]
                                       for n in ELECTION_SCALES))
    for n in sorted(cells):
        print('# election_cell %s' % json.dumps(cells[n]),
              file=sys.stderr)

    for n in ELECTION_SCALES:
        if rows[n]:
            p50, p99 = _percentiles(rows[n])
            print(json.dumps({
                'metric': 'election_failover_ms',
                'members': n,
                'rounds': len(rows[n]),
                'p50_ms': round(p50, 3),
                'p99_ms': round(p99, 3),
            }), flush=True)
    paired = paired_rounds
    if paired:
        wins = sum(1 for x, y in paired if x < y)   # 3-member faster
        losses = sum(1 for x, y in paired if x > y)
        deltas = [(y - x) / x * 100.0 for x, y in paired if x]
        print(json.dumps({
            'metric': 'election_members_sign_test',
            'pair': '%d-vs-%d-members' % ELECTION_SCALES,
            'rounds': len(paired),
            'wins_smaller_faster': wins,
            'losses': losses,
            'mean_delta_pct': round(sum(deltas)
                                    / max(1, len(deltas)), 1),
            'sign_p': round(sign_test_p(wins, losses), 4),
        }), flush=True)


#: `bench.py --reconfig` per-arm write counts: each arm keeps
#: writing until the concurrent membership change completes, with at
#: least MIN and at most CAP acked sets, so the paired p50s compare
#: like against like while the cell stays bounded.
RECONFIG_MIN_OPS = 60
RECONFIG_CAP_OPS = 400


async def _reconfig_round(idx: int) -> dict:
    """One dynamic-membership cell: fresh 3-voter + 1-observer
    in-process ensemble, one client writing sequentially.  Three
    adjacent arms on the same ensemble: steady state, during an
    OBSERVER JOIN (snapshot bootstrap + attach + CONTROL record),
    and during a VOTER REPLACE (joint-majority handoff).  Returns
    per-arm write p50 plus the wall duration of each change."""
    import asyncio as aio
    import time as _t

    from zkstream_tpu import Client
    from zkstream_tpu.server import ZKEnsemble

    ens = await ZKEnsemble(3, observers=1, seed=300 + idx).start()
    c = Client(servers=ens.addresses(), shuffle_backends=False,
               session_timeout=8000)
    c.start()

    def p50(lats: list) -> float:
        return sorted(lats)[len(lats) // 2]

    async def burst(until=None) -> list:
        """Sequential acked sets; with ``until`` keeps writing while
        the membership change runs (>= MIN, <= CAP ops)."""
        lats = []
        i = 0
        while True:
            t0 = _t.perf_counter()
            await c.set('/rw', b'x%d' % (i,), version=-1)
            lats.append((_t.perf_counter() - t0) * 1000.0)
            i += 1
            if until is None:
                if i >= RECONFIG_MIN_OPS:
                    return lats
            elif (until.done() and i >= RECONFIG_MIN_OPS) \
                    or i >= RECONFIG_CAP_OPS:
                return lats

    try:
        await c.wait_connected(timeout=10)
        await c.create('/rw', b'w')
        steady = await burst()
        t0 = _t.perf_counter()
        join = aio.ensure_future(ens.add_observer())
        during_join = await burst(until=join)
        await join
        join_ms = (_t.perf_counter() - t0) * 1000.0
        t0 = _t.perf_counter()
        rep = aio.ensure_future(ens.replace_voter(2))
        during_replace = await burst(until=rep)
        await rep
        replace_ms = (_t.perf_counter() - t0) * 1000.0
        return {'steady_p50_ms': round(p50(steady), 3),
                'join_p50_ms': round(p50(during_join), 3),
                'replace_p50_ms': round(p50(during_replace), 3),
                'observer_join_ms': round(join_ms, 3),
                'voter_replace_ms': round(replace_ms, 3),
                'config_version': ens.db.config_version}
    finally:
        await c.close()
        await ens.stop()


def bench_reconfig() -> None:
    """The dynamic-membership cost envelope (`make bench-reconfig`):
    per-round adjacent steady / during-observer-join /
    during-voter-replace write cells on one ensemble, exact
    two-sided sign tests against the steady arm.  The acceptance bar
    (README "Dynamic membership") is that the OBSERVER JOIN arm is
    NOT significantly slower — an observer never widens the write
    quorum, so attaching one must not tax the write path.  The voter
    replace arm is reported without a bar: a joint window briefly
    holds writes to two majorities by design.  Rounds via
    ZKSTREAM_BENCH_RECONFIG_ROUNDS."""
    import asyncio

    from zkstream_tpu.utils.metrics import sign_test_p

    rounds = int(os.environ.get('ZKSTREAM_BENCH_RECONFIG_ROUNDS',
                                '10'))
    rows: dict = {'steady': [], 'join': [], 'replace': []}
    durs: dict = {'observer_join_ms': [], 'voter_replace_ms': []}
    paired: list = []
    for rnd in range(rounds):
        try:
            r = asyncio.run(_reconfig_round(rnd))
        except Exception as e:
            print('# reconfig round %d failed: %r' % (rnd, e),
                  file=sys.stderr)
            continue
        print('# reconfig_cell %s' % json.dumps(r), file=sys.stderr)
        rows['steady'].append(r['steady_p50_ms'])
        rows['join'].append(r['join_p50_ms'])
        rows['replace'].append(r['replace_p50_ms'])
        durs['observer_join_ms'].append(r['observer_join_ms'])
        durs['voter_replace_ms'].append(r['voter_replace_ms'])
        paired.append((r['steady_p50_ms'], r['join_p50_ms'],
                       r['replace_p50_ms']))
    for arm in ('steady', 'join', 'replace'):
        if rows[arm]:
            p50, p99 = _percentiles(rows[arm])
            print(json.dumps({
                'metric': 'reconfig_write_p50_ms',
                'arm': arm,
                'rounds': len(rows[arm]),
                'p50_ms': round(p50, 3),
                'p99_ms': round(p99, 3),
            }), flush=True)
    for name, vals in durs.items():
        if vals:
            p50, p99 = _percentiles(vals)
            print(json.dumps({
                'metric': name, 'rounds': len(vals),
                'p50_ms': round(p50, 3), 'p99_ms': round(p99, 3),
            }), flush=True)
    for arm, col in (('join', 1), ('replace', 2)):
        if not paired:
            continue
        wins = sum(1 for t in paired if t[col] > t[0])   # arm slower
        losses = sum(1 for t in paired if t[col] < t[0])
        deltas = [(t[col] - t[0]) / t[0] * 100.0
                  for t in paired if t[0]]
        print(json.dumps({
            'metric': 'reconfig_%s_sign_test' % (arm,),
            'pair': 'steady-vs-during-%s' % (arm,),
            'rounds': len(paired),
            'slower': wins,
            'faster': losses,
            'mean_delta_pct': round(sum(deltas)
                                    / max(1, len(deltas)), 1),
            'sign_p': round(sign_test_p(wins, losses), 4),
        }), flush=True)


#: `bench.py --quorum` ensemble sizes (the acceptance envelope:
#: quorum-on must not be significantly slower than quorum-off at
#: either membership — with synchronous in-process replicas the gate
#: clears at flush time and its cost is bookkeeping).
QUORUM_SCALES = (3, 5)
#: MULTI batching cells: one multi of K creates vs K pipelined
#: singleton creates (same client, same server, adjacent runs).
MULTI_BATCHES = (4, 16)
QUORUM_OPS = 200


async def _quorum_round(members: int, quorum_on: bool) -> dict:
    """One write-heavy cell against a fresh in-process ensemble with
    the quorum gate on or off: sequential acked sets through the
    leader, headline set ops/s plus the zk_quorum_ack_ms scrape."""
    import asyncio as aio

    from zkstream_tpu import Client
    from zkstream_tpu.server import ZKEnsemble
    from zkstream_tpu.server.replication import METRIC_QUORUM_ACK
    from zkstream_tpu.utils.metrics import Collector

    collector = Collector()
    ens = await ZKEnsemble(members, quorum=quorum_on,
                           collector=collector).start()
    c = Client(servers=ens.addresses(), shuffle_backends=False,
               session_timeout=8000)
    c.start()
    loop = aio.get_running_loop()
    try:
        await c.wait_connected(timeout=10)
        await c.create('/q', b'w')
        for _ in range(10):
            await c.set('/q', b'warm', version=-1)
        t0 = loop.time()
        for i in range(QUORUM_OPS):
            await c.set('/q', b'v%d' % (i,), version=-1)
        dt = loop.time() - t0
        out = {'members': members,
               'quorum': 'on' if quorum_on else 'off',
               'set': {'ops_per_sec': round(QUORUM_OPS / dt, 1)}}
        if quorum_on:
            hist = collector.get_collector(METRIC_QUORUM_ACK)
            n = hist.count()
            if n:
                out['quorum_ack'] = {
                    'count': n,
                    'p50_ms': round(hist.percentile(50), 3),
                    'p99_ms': round(hist.percentile(99), 3)}
            out['quorum_degraded'] = ens.quorum.degraded_releases
        return out
    finally:
        await c.close()
        await ens.stop()


async def _multi_round(k: int) -> dict:
    """One batching cell: K pipelined singleton creates vs ONE multi
    of K creates, adjacent on the same client/server — sub-op
    throughput both ways."""
    import asyncio as aio

    from zkstream_tpu import Client
    from zkstream_tpu.server import ZKServer

    srv = await ZKServer().start()
    c = Client(address='127.0.0.1', port=srv.port)
    c.start()
    loop = aio.get_running_loop()
    try:
        await c.wait_connected(timeout=10)
        await c.create('/warm', b'')
        reps = max(1, 64 // k)
        t0 = loop.time()
        for r in range(reps):
            await aio.gather(*[
                c.create('/s%d-%d' % (r, i), b'x')
                for i in range(k)])
        dt_single = loop.time() - t0
        t0 = loop.time()
        for r in range(reps):
            await c.multi([
                {'op': 'create', 'path': '/m%d-%d' % (r, i),
                 'data': b'x'}
                for i in range(k)])
        dt_multi = loop.time() - t0
        n = reps * k
        return {'batch': k,
                'singleton_subops_per_sec': round(n / dt_single, 1),
                'multi_subops_per_sec': round(n / dt_multi, 1)}
    finally:
        await c.close()
        await srv.stop()


def bench_quorum() -> None:
    """The quorum-commit cost envelope (`make bench-quorum`): paired
    quorum-on/off write-heavy cells at 3/5 members, plus
    MULTI-vs-N-singletons batching cells — per-round adjacent runs,
    exact two-sided sign tests (the acceptance bar: neither quorum-on
    nor MULTI significantly slower in any paired cell).  Rounds via
    ZKSTREAM_BENCH_QUORUM_ROUNDS; the measured table lives in
    PROFILE.md "Quorum commit"."""
    import asyncio

    from zkstream_tpu.utils.metrics import sign_test_p

    rounds = int(os.environ.get('ZKSTREAM_BENCH_QUORUM_ROUNDS', '10'))
    rows: dict = {}
    cells: dict = {}
    mrows: dict = {k: [] for k in MULTI_BATCHES}
    for _rnd in range(rounds):
        for n in QUORUM_SCALES:
            for q_on in (True, False):
                try:
                    r = asyncio.run(_quorum_round(n, q_on))
                except Exception as e:
                    print('# quorum cell %s@%d round failed: %r'
                          % ('on' if q_on else 'off', n, e),
                          file=sys.stderr)
                    continue
                key = (n, 'on' if q_on else 'off')
                rows.setdefault(key, []).append(
                    r['set']['ops_per_sec'])
                if key not in cells or r['set']['ops_per_sec'] > \
                        cells[key]['set']['ops_per_sec']:
                    cells[key] = r
        for k in MULTI_BATCHES:
            try:
                r = asyncio.run(_multi_round(k))
            except Exception as e:
                print('# multi cell batch=%d round failed: %r'
                      % (k, e), file=sys.stderr)
                continue
            mrows[k].append((r['multi_subops_per_sec'],
                             r['singleton_subops_per_sec']))
            mkey = ('multi', k)
            if mkey not in cells or r['multi_subops_per_sec'] > \
                    cells[mkey]['multi_subops_per_sec']:
                cells[mkey] = r
    for key in sorted(cells, key=str):
        print('# quorum_cell %s' % json.dumps(cells[key]),
              file=sys.stderr)
    for n in QUORUM_SCALES:
        a = rows.get((n, 'on'), [])
        b = rows.get((n, 'off'), [])
        if not a or not b:
            continue
        paired = list(zip(a, b))
        deltas = [(x - y) / y * 100.0 for x, y in paired if y]
        wins = sum(1 for x, y in paired if x > y)
        losses = sum(1 for x, y in paired if x < y)
        print(json.dumps({
            'metric': 'quorum_commit_sign_test',
            'pair': 'on-vs-off',
            'members': n,
            'rounds': len(paired),
            'wins': wins,
            'losses': losses,
            'mean_delta_pct': round(sum(deltas)
                                    / max(1, len(deltas)), 1),
            'sign_p': round(sign_test_p(wins, losses), 4),
        }), flush=True)
    for k in MULTI_BATCHES:
        paired = mrows[k]
        if not paired:
            continue
        deltas = [(x - y) / y * 100.0 for x, y in paired if y]
        wins = sum(1 for x, y in paired if x > y)
        losses = sum(1 for x, y in paired if x < y)
        print(json.dumps({
            'metric': 'multi_batching_sign_test',
            'pair': 'multi-vs-%d-singletons' % (k,),
            'batch': k,
            'rounds': len(paired),
            'wins': wins,
            'losses': losses,
            'mean_delta_pct': round(sum(deltas)
                                    / max(1, len(deltas)), 1),
            'sign_p': round(sign_test_p(wins, losses), 4),
        }), flush=True)


#: `bench.py --traceov` fleet sizes (the acceptance envelope: the
#: server trace plane — member span rings + tick ledger — must not be
#: significantly slower than the untraced arm at either scale).
TRACE_SCALES = (16, 64)


def bench_trace_overhead() -> None:
    """The server trace plane's cost envelope (`make bench-trace`):
    paired write-heavy cells — trace plane on (the default: member
    span rings + tick ledger) vs ``ZKSTREAM_NO_SERVER_TRACE=1`` — at
    fleet 16/64.  Per-round adjacent A/B runs with the arm order
    ALTERNATING per round: on this image the first cell of an
    adjacent pair runs measurably slower regardless of arm (observed
    ~10-15 % first-slot penalty over a 4-round A/A probe), and a
    fixed order folds that bias straight into the sign test.  Sign of
    the per-round headline (set ops/s) delta, exact two-sided sign
    test: otherwise the same PROFILE.md methodology as the cork, WAL
    and fan-out families."""
    import asyncio

    from zkstream_tpu.utils import native
    from zkstream_tpu.utils.metrics import sign_test_p

    mode = 'native' if native.ensure_lib() is not None else 'python'
    rounds = int(os.environ.get('ZKSTREAM_BENCH_TRACE_ROUNDS', '10'))
    # the arms toggle the env var the server reads at construction;
    # snapshot and restore any operator-set value, and force BOTH
    # states explicitly — an inherited ZKSTREAM_NO_SERVER_TRACE=1
    # would otherwise turn the traced arm into a second untraced one
    ambient = os.environ.get('ZKSTREAM_NO_SERVER_TRACE')
    rows: dict = {}
    cells: dict = {}
    try:
        for rnd in range(rounds):
            arms = (('traced', 'untraced') if rnd % 2 == 0
                    else ('untraced', 'traced'))
            for n in TRACE_SCALES:
                # the sign test pairs ADJACENT A/B runs: a round where
                # either arm failed contributes to neither, so the
                # surviving pairs stay aligned round-for-round (the
                # fan-out family's rule)
                pair: dict = {}
                for arm in arms:
                    if arm == 'untraced':
                        os.environ['ZKSTREAM_NO_SERVER_TRACE'] = '1'
                    else:
                        os.environ.pop('ZKSTREAM_NO_SERVER_TRACE',
                                       None)
                    try:
                        r = asyncio.run(_client_ops_run(
                            mode, n, write_heavy=True))
                    except Exception as e:
                        print('# trace cell %s@%d round failed: %r'
                              % (arm, n, e), file=sys.stderr)
                        continue
                    r['trace_arm'] = arm
                    pair[arm] = r
                for arm, r in pair.items():
                    key = (n, arm)
                    if len(pair) == 2:
                        rows.setdefault(key, []).append(
                            r['set']['ops_per_sec'])
                    if key not in cells or r['set']['ops_per_sec'] > \
                            cells[key]['set']['ops_per_sec']:
                        cells[key] = r
    finally:
        if ambient is None:
            os.environ.pop('ZKSTREAM_NO_SERVER_TRACE', None)
        else:
            os.environ['ZKSTREAM_NO_SERVER_TRACE'] = ambient
    for key in sorted(cells, key=str):
        print('# trace_cell %s' % json.dumps(cells[key]),
              file=sys.stderr)
    for n in TRACE_SCALES:
        a = rows.get((n, 'traced'), [])
        b = rows.get((n, 'untraced'), [])
        if not a or not b:
            continue
        paired = list(zip(a, b))
        deltas = [(x - y) / y * 100.0 for x, y in paired if y]
        wins = sum(1 for x, y in paired if x > y)
        losses = sum(1 for x, y in paired if x < y)
        print(json.dumps({
            'metric': 'trace_plane_sign_test',
            'pair': 'traced-vs-untraced',
            'conns': n,
            'rounds': len(paired),
            'wins': wins,
            'losses': losses,
            'mean_delta_pct': round(sum(deltas)
                                    / max(1, len(deltas)), 1),
            'sign_p': round(sign_test_p(wins, losses), 4),
        }), flush=True)


#: `bench.py --blackbox` fleet sizes (the acceptance envelope: the
#: flight recorder — periodic frames + slow-op digest off the hot
#: path — must not be significantly slower than the recorder-off arm
#: at either scale).
BLACKBOX_SCALES = (16, 64)


def bench_blackbox_overhead() -> None:
    """The black-box plane's cost envelope (`make bench-blackbox`):
    paired write-heavy WAL-backed cells — flight recorder on (the
    default: periodic snapshot frames + slow-op digest, written on
    the executor) vs ``ZKSTREAM_NO_BLACKBOX=1`` — at fleet 16/64.
    WAL 'tick' cells on purpose: only a server with a wal_dir has a
    recorder at all, and the recorder shares the executor with the
    group fsync — the one interaction that could plausibly cost.
    Per-round adjacent A/B with the arm order ALTERNATING per round
    (the first-slot penalty rationale in bench_trace_overhead), sign
    of the per-round set-ops/s delta, exact two-sided sign test —
    the PROFILE.md methodology shared by every paired family."""
    import asyncio

    from zkstream_tpu.utils import native
    from zkstream_tpu.utils.metrics import sign_test_p

    mode = 'native' if native.ensure_lib() is not None else 'python'
    rounds = int(os.environ.get('ZKSTREAM_BENCH_BLACKBOX_ROUNDS',
                                '10'))
    # both arm states forced explicitly, ambient value restored — an
    # inherited ZKSTREAM_NO_BLACKBOX=1 would silently turn the
    # recorded arm into a second unrecorded one
    ambient = os.environ.get('ZKSTREAM_NO_BLACKBOX')
    rows: dict = {}
    cells: dict = {}
    try:
        for rnd in range(rounds):
            arms = (('blackbox', 'nobox') if rnd % 2 == 0
                    else ('nobox', 'blackbox'))
            for n in BLACKBOX_SCALES:
                pair: dict = {}
                for arm in arms:
                    if arm == 'nobox':
                        os.environ['ZKSTREAM_NO_BLACKBOX'] = '1'
                    else:
                        os.environ.pop('ZKSTREAM_NO_BLACKBOX', None)
                    try:
                        r = asyncio.run(_client_ops_run(
                            mode, n, write_heavy=True, wal='tick'))
                    except Exception as e:
                        print('# blackbox cell %s@%d round failed: '
                              '%r' % (arm, n, e), file=sys.stderr)
                        continue
                    r['blackbox_arm'] = arm
                    pair[arm] = r
                for arm, r in pair.items():
                    key = (n, arm)
                    if len(pair) == 2:
                        # adjacent pairs only: a round where either
                        # arm failed contributes to neither
                        rows.setdefault(key, []).append(
                            r['set']['ops_per_sec'])
                    if key not in cells or r['set']['ops_per_sec'] \
                            > cells[key]['set']['ops_per_sec']:
                        cells[key] = r
    finally:
        if ambient is None:
            os.environ.pop('ZKSTREAM_NO_BLACKBOX', None)
        else:
            os.environ['ZKSTREAM_NO_BLACKBOX'] = ambient
    for key in sorted(cells, key=str):
        print('# blackbox_cell %s' % json.dumps(cells[key]),
              file=sys.stderr)
    for n in BLACKBOX_SCALES:
        a = rows.get((n, 'blackbox'), [])
        b = rows.get((n, 'nobox'), [])
        if not a or not b:
            continue
        paired = list(zip(a, b))
        deltas = [(x - y) / y * 100.0 for x, y in paired if y]
        wins = sum(1 for x, y in paired if x > y)
        losses = sum(1 for x, y in paired if x < y)
        print(json.dumps({
            'metric': 'blackbox_plane_sign_test',
            'pair': 'blackbox-vs-off',
            'conns': n,
            'rounds': len(paired),
            'wins': wins,
            'losses': losses,
            'mean_delta_pct': round(sum(deltas)
                                    / max(1, len(deltas)), 1),
            'sign_p': round(sign_test_p(wins, losses), 4),
        }), flush=True)


#: `bench.py --overload` fleet sizes for the plane-overhead family
#: (the acceptance envelope: the overload plane's accounting must not
#: be significantly slower than ``ZKSTREAM_NO_OVERLOAD=1``).
OVERLOAD_SCALES = (16, 64)
#: Stalled pipelining readers per defense cell, and the reads each
#: one aims at the member's tx account (32 KiB replies apiece).
OVERLOAD_STALLED = 3
OVERLOAD_STALLED_READS = 60


async def _overload_defense_round(defense: bool) -> dict:
    """One stalled-consumer defense cell: a writer fans out sets to a
    healthy watcher while OVERLOAD_STALLED subscribers stop reading
    and pipeline fat gets — the wedged-socket reply backlog the hard
    watermark exists for.  Returns the writer's set throughput, the
    healthy watcher's observed fires, the peak per-connection tx
    backlog the member carried, and the defense counters (zero on the
    no-defense arm, where the backlog is the point of the row)."""
    import asyncio
    import time as _time

    from zkstream_tpu import Client
    from zkstream_tpu.io.backoff import BackoffPolicy
    from zkstream_tpu.io.overload import OverloadConfig
    from zkstream_tpu.server import ZKServer

    fast = dict(
        connect_policy=BackoffPolicy(timeout=300, retries=2, delay=30,
                                     cap=200),
        default_policy=BackoffPolicy(timeout=500, retries=3, delay=20,
                                     cap=120))
    if defense:
        srv = await ZKServer(overload_config=OverloadConfig(
            tx_soft=8 * 1024, tx_hard=64 * 1024)).start()
    else:
        srv = await ZKServer(overload=False).start()
    cls = [Client(address='127.0.0.1', port=srv.port, **fast)
           for _ in range(2 + OVERLOAD_STALLED)]
    writer, healthy, stalled = cls[0], cls[1], cls[2:]
    pending: list = []
    try:
        for c in cls:
            c.start()
            await c.wait_connected(timeout=5)
        await writer.create('/fan', b'f')
        await writer.create('/big', b'p' * (32 * 1024))
        fires: list = []
        healthy.watcher('/fan').on(
            'dataChanged', lambda data, stat: fires.append(1))
        while not fires:
            await asyncio.sleep(0.005)
        import socket as socketmod
        for c in stalled:
            tr = c.current_connection().transport
            sock = tr.get_extra_info('socket')
            if sock is not None:
                # shrink the stalled reader's receive window so the
                # kernel can't mask the backlog — the member's own tx
                # account is what the cell measures
                sock.setsockopt(socketmod.SOL_SOCKET,
                                socketmod.SO_RCVBUF, 4096)
            tr.pause_reading()
            pending.extend(asyncio.ensure_future(c.get('/big'))
                           for _ in range(OVERLOAD_STALLED_READS))
        await asyncio.sleep(0)
        # a tight background sampler: the cork drains at tick
        # boundaries, so only a between-callbacks probe sees the real
        # backlog crest (post-await samples always land after flush)
        peak = [0]

        async def _sample() -> None:
            while True:
                peak[0] = max(peak[0], max(
                    (c._tx.buffered_bytes() for c in srv.conns
                     if not c.closed), default=0))
                await asyncio.sleep(0)
        sampler = asyncio.ensure_future(_sample())
        t0 = _time.perf_counter()
        for _ in range(100):
            await writer.set('/fan', b'f', version=-1)
        dt = _time.perf_counter() - t0
        sampler.cancel()
        await asyncio.gather(sampler, return_exceptions=True)
        ov = srv.overload
        return {
            'defense': defense,
            'set_ops_per_sec': round(100 / dt, 1),
            'healthy_fires': len(fires),
            'peak_tx_buffered': peak[0],
            'evictions': ov.evictions if ov is not None else 0,
            'notifications_dropped':
                ov.notifications_dropped if ov is not None else 0,
        }
    finally:
        for t in pending:
            t.cancel()
        await asyncio.gather(*pending, return_exceptions=True)
        for c in cls:
            try:
                await asyncio.wait_for(c.close(), 5)
            except Exception:
                pass
        await srv.stop()


def bench_overload() -> None:
    """The overload plane's cost + defense envelope (`make
    bench-overload`), two paired families:

    - **defense cells** — the stalled-consumer scenario above,
      defense on vs ``overload=False``: the on-arm's peak tx backlog
      must stay bounded by the hard watermark while the off-arm's
      grows with the pipelined reads, and the writer's fan-out
      throughput must not be significantly SLOWER with the defense
      (sign of the per-round set-ops/s delta, exact two-sided test);
    - **overhead cells** — healthy write-heavy client-ops runs,
      plane on vs ``ZKSTREAM_NO_OVERLOAD=1`` at fleet 16/64 with the
      arm order alternating per round (the first-slot penalty
      rationale in bench_trace_overhead): the plane's per-op
      accounting must not be significantly slower.

    Rounds via ZKSTREAM_BENCH_OVERLOAD_ROUNDS; the measured tables
    live in PROFILE.md "Overload plane"."""
    import asyncio as _aio

    from zkstream_tpu.utils import native
    from zkstream_tpu.utils.metrics import sign_test_p

    rounds = int(os.environ.get('ZKSTREAM_BENCH_OVERLOAD_ROUNDS',
                                '8'))
    drows: list = []
    dcells: dict = {}
    for rnd in range(rounds):
        arms = ((True, False) if rnd % 2 == 0 else (False, True))
        pair: dict = {}
        for defense in arms:
            try:
                pair[defense] = _aio.run(
                    _overload_defense_round(defense))
            except Exception as e:
                print('# overload defense cell %s round failed: %r'
                      % ('on' if defense else 'off', e),
                      file=sys.stderr)
        for defense, r in pair.items():
            key = 'on' if defense else 'off'
            if key not in dcells or r['set_ops_per_sec'] > \
                    dcells[key]['set_ops_per_sec']:
                dcells[key] = r
        if len(pair) == 2:
            drows.append((pair[True]['set_ops_per_sec'],
                          pair[False]['set_ops_per_sec'],
                          pair[True]['peak_tx_buffered'],
                          pair[False]['peak_tx_buffered']))
    for key in sorted(dcells):
        print('# overload_defense_cell %s' % json.dumps(dcells[key]),
              file=sys.stderr)
    if drows:
        deltas = [(a - b) / b * 100.0 for a, b, _, _ in drows if b]
        wins = sum(1 for a, b, _, _ in drows if a > b)
        losses = sum(1 for a, b, _, _ in drows if a < b)
        print(json.dumps({
            'metric': 'overload_defense_sign_test',
            'pair': 'defense-vs-off',
            'stalled': OVERLOAD_STALLED,
            'rounds': len(drows),
            'wins': wins,
            'losses': losses,
            'mean_delta_pct': round(sum(deltas)
                                    / max(1, len(deltas)), 1),
            'sign_p': round(sign_test_p(wins, losses), 4),
            'peak_tx_on': max(p for _, _, p, _ in drows),
            'peak_tx_off': max(p for _, _, _, p in drows),
        }), flush=True)
    mode = 'native' if native.ensure_lib() is not None else 'python'
    # both arm states forced explicitly, ambient value restored — an
    # inherited ZKSTREAM_NO_OVERLOAD=1 would silently turn the
    # defended arm into a second undefended one
    ambient = os.environ.get('ZKSTREAM_NO_OVERLOAD')
    rows: dict = {}
    cells: dict = {}
    try:
        for rnd in range(rounds):
            arms = (('overload', 'nooverload') if rnd % 2 == 0
                    else ('nooverload', 'overload'))
            for n in OVERLOAD_SCALES:
                pair = {}
                for arm in arms:
                    if arm == 'nooverload':
                        os.environ['ZKSTREAM_NO_OVERLOAD'] = '1'
                    else:
                        os.environ.pop('ZKSTREAM_NO_OVERLOAD', None)
                    try:
                        r = _aio.run(_client_ops_run(
                            mode, n, write_heavy=True))
                    except Exception as e:
                        print('# overload cell %s@%d round failed: '
                              '%r' % (arm, n, e), file=sys.stderr)
                        continue
                    r['overload_arm'] = arm
                    pair[arm] = r
                for arm, r in pair.items():
                    key = (n, arm)
                    if len(pair) == 2:
                        rows.setdefault(key, []).append(
                            r['set']['ops_per_sec'])
                    if key not in cells or r['set']['ops_per_sec'] \
                            > cells[key]['set']['ops_per_sec']:
                        cells[key] = r
    finally:
        if ambient is None:
            os.environ.pop('ZKSTREAM_NO_OVERLOAD', None)
        else:
            os.environ['ZKSTREAM_NO_OVERLOAD'] = ambient
    for key in sorted(cells, key=str):
        print('# overload_cell %s' % json.dumps(cells[key]),
              file=sys.stderr)
    for n in OVERLOAD_SCALES:
        a = rows.get((n, 'overload'), [])
        b = rows.get((n, 'nooverload'), [])
        if not a or not b:
            continue
        paired = list(zip(a, b))
        deltas = [(x - y) / y * 100.0 for x, y in paired if y]
        wins = sum(1 for x, y in paired if x > y)
        losses = sum(1 for x, y in paired if x < y)
        print(json.dumps({
            'metric': 'overload_plane_sign_test',
            'pair': 'overload-vs-off',
            'conns': n,
            'rounds': len(paired),
            'wins': wins,
            'losses': losses,
            'mean_delta_pct': round(sum(deltas)
                                    / max(1, len(deltas)), 1),
            'sign_p': round(sign_test_p(wins, losses), 4),
        }), flush=True)


#: `bench.py --fanout` sweep (the serving-plane cell family): sessions
#: on the box x watchers on the hot path.  -1 = every session watches.
FANOUT_SESSIONS = (1000, 10000, 100000)
FANOUT_WATCHERS = (1, 100, -1)


class _NullWriter:
    """A transport sink for fan-out cells: counts what the server
    writes, delivers nowhere.  The cell measures the serving plane's
    dispatch + encode + flush path (the thing the watch table owns);
    100k real sockets would measure the kernel instead."""

    __slots__ = ('nbytes', 'writes', 'sink')

    def __init__(self, sink):
        self.nbytes = 0
        self.writes = 0
        self.sink = sink

    def write(self, data):
        self.nbytes += len(data)
        self.writes += 1
        self.sink[0] += len(data)

    def close(self):
        pass

    def get_extra_info(self, name, default=None):
        return default


#: Measured decode ceiling of ONE Python client pump (round 15 ran 8
#: read_worker processes into ~89k ops/s aggregate, ~11k/s each — the
#: "server" ceiling was the client's).  Every cell a Python client
#: drives carries ``client_capped: true`` plus this number so its
#: absolute throughput can't be mistaken for a server limit; the C
#: loadgen cells (tools/loadgen.c) carry ``client_capped: false``.
PY_CLIENT_CEILING_OPS = 11000


async def fanout_cell(sessions: int, watchers: int, table: bool,
                      events: int | None = None,
                      collector=None) -> dict:
    """One serving-plane fan-out measurement: ``sessions`` in-process
    server connections over a null transport, ``watchers`` of them
    holding a data watch on one hot path.  Fires ``events`` SET_DATA
    mutations (re-arming between events) and times each
    mutation -> all-notification-bytes-flushed window.

    ``table=True`` runs the sharded watch table
    (server/watchtable.py); ``table=False`` the per-connection emitter
    fallback — the paired arm, where every event costs O(sessions)
    callbacks regardless of ``watchers``."""
    import asyncio

    from zkstream_tpu.protocol.consts import CreateFlag
    from zkstream_tpu.server import ZKDatabase, ZKServer
    from zkstream_tpu.server.server import ServerConnection

    loop = asyncio.get_running_loop()
    db = ZKDatabase()
    # never started: no listener, no kernel sockets — connections are
    # wired straight to null transports below
    srv = ZKServer(db=db, watchtable=table, collector=collector)
    total = [0]
    conns = []
    for _ in range(sessions):
        conn = ServerConnection(srv, reader=None,
                                writer=_NullWriter(total))
        conn._subscribe()
        srv.conns.add(conn)
        conns.append(conn)
    db.create('/hot', b'', [], CreateFlag(0))
    watcher_conns = conns[:watchers]
    # one frame's wire size (constant per event: fixed-width header +
    # this path), to know when an event's fan-out has fully flushed
    frame_len = len(srv.encode_notification('DATA_CHANGED', '/hot', 1))
    if events is None:
        # emitter-arm cost is O(sessions) per event: keep big cells
        # bounded, small cells statistically useful
        events = max(3, min(30, 200000 // max(sessions, 1)))
    lat_ms = []
    payload = b'z' * 64
    try:
        for _ in range(events):
            for c in watcher_conns:
                c._arm_data('/hot')
            expect = total[0] + watchers * frame_len
            t0 = loop.time()
            db.set_data('/hot', payload, -1)
            deadline = t0 + 30.0
            while total[0] < expect:
                await asyncio.sleep(0)
                if loop.time() > deadline:
                    raise TimeoutError(
                        'fan-out stalled: %d/%d bytes'
                        % (total[0], expect))
            lat_ms.append((loop.time() - t0) * 1000.0)
    finally:
        if not table:
            # The emitter arm's clean close is O(listeners) PER
            # CONNECTION (EventEmitter.remove_listener scans the
            # store's listener list), i.e. O(sessions^2) for the whole
            # fleet — hours at 100k, and itself part of why the table
            # exists (table-mode close is O(paths watched)).  The cell
            # measures dispatch, not teardown: drop the listeners
            # wholesale first so close() sees empty lists.
            for evt in ('created', 'deleted', 'dataChanged',
                        'childrenChanged'):
                db.remove_all_listeners(evt)
        for c in conns:
            c.close()
        if srv.ledger is not None:
            srv.ledger.close_tick()   # flush the residual burst
    p50, p99 = _percentiles(lat_ms)
    out = {'sessions': sessions, 'watchers': watchers,
           'table': table, 'events': events,
           # paired A/B cell driven by one in-process Python loop:
           # relative deltas are honest, absolute rates are capped by
           # the Python driver (see the loadgen fan-out cells)
           'client_capped': True,
           'client_ceiling_ops_per_sec': PY_CLIENT_CEILING_OPS,
           'event_ms_mean': round(sum(lat_ms) / len(lat_ms), 3),
           'event_ms_p50': round(p50, 3),
           'event_ms_p99': round(p99, 3),
           'notifs_per_sec': round(
               watchers * events / (sum(lat_ms) / 1000.0), 1)}
    if collector is not None and table:
        from zkstream_tpu.server.watchtable import METRIC_FANOUT_TICK
        try:
            tick = collector.get_collector(METRIC_FANOUT_TICK)
        except ValueError:
            tick = None
        if tick is not None and tick.count({'plane': 'fanout'}):
            labels = {'plane': 'fanout'}
            out['fanout_tick_ms'] = {
                'count': tick.count(labels),
                'p50': round(tick.percentile(50, labels), 3),
                'p99': round(tick.percentile(99, labels), 3)}
        from zkstream_tpu.io.sendplane import scrape_flush_cells
        flush = scrape_flush_cells(collector).get('fanout')
        if flush:
            out['fanout_flush_batches'] = flush
    if collector is not None:
        from zkstream_tpu.utils.metrics import scrape_tick_cells
        tick = scrape_tick_cells(collector)
        if tick:
            out['tick_ledger'] = tick
    return out


def _arg_ints(flag: str) -> list[int] | None:
    """Parse ``--flag 1000,10000`` style comma-lists from sys.argv."""
    if flag not in sys.argv:
        return None
    idx = sys.argv.index(flag)
    if idx + 1 >= len(sys.argv):
        return None
    return [int(x) for x in sys.argv[idx + 1].split(',') if x]


def bench_fanout() -> None:
    """The serving-plane fan-out envelope (`make bench-fanout`):
    paired table-vs-emitter cells over the sessions x watchers sweep,
    per-round adjacent A/B runs, exact two-sided sign test on the
    per-event fan-out latency — PROFILE.md methodology, same as the
    cork and WAL families.  The acceptance bar: the table is not
    significantly slower at any cell and significantly faster at the
    high-watcher/low-coverage cells where the emitter pays
    O(sessions) per event.  Scale with ZKSTREAM_BENCH_FANOUT_ROUNDS;
    narrow the sweep with ``--sessions/--watchers`` comma-lists."""
    import asyncio

    from zkstream_tpu.utils.metrics import Collector, sign_test_p

    sessions_sweep = _arg_ints('--sessions') or list(FANOUT_SESSIONS)
    watchers_sweep = _arg_ints('--watchers') or list(FANOUT_WATCHERS)
    rounds = int(os.environ.get('ZKSTREAM_BENCH_FANOUT_ROUNDS', '10'))
    rows: dict = {}
    cells: dict = {}
    for rnd in range(rounds):
        for s in sessions_sweep:
            for w in watchers_sweep:
                wn = s if w < 0 else w
                if wn > s:
                    continue
                # the sign test pairs ADJACENT A/B runs: a round where
                # either arm failed contributes to neither, so the
                # surviving pairs stay aligned round-for-round
                pair = {}
                for arm_table in (True, False):
                    col = Collector()
                    try:
                        pair[arm_table] = asyncio.run(fanout_cell(
                            s, wn, arm_table, collector=col))
                    except Exception as e:
                        print('# fanout cell %dx%d table=%s round '
                              'failed: %r' % (s, wn, arm_table, e),
                              file=sys.stderr)
                for arm_table, r in pair.items():
                    key = (s, wn, 'table' if arm_table else 'emitter')
                    if len(pair) == 2:
                        rows.setdefault(key, []).append(
                            r['event_ms_mean'])
                    if key not in cells or r['event_ms_mean'] < \
                            cells[key]['event_ms_mean']:
                        cells[key] = r
    for key in sorted(cells, key=str):
        print('# fanout_cell %s' % json.dumps(cells[key]),
              file=sys.stderr)
    for s in sessions_sweep:
        for w in watchers_sweep:
            wn = s if w < 0 else w
            if wn > s:
                continue
            a = rows.get((s, wn, 'table'), [])
            b = rows.get((s, wn, 'emitter'), [])
            if not a or not b:
                continue
            paired = list(zip(a, b))
            # positive delta = table faster (lower per-event latency)
            deltas = [(y - x) / y * 100.0 for x, y in paired if y]
            wins = sum(1 for x, y in paired if x < y)
            losses = sum(1 for x, y in paired if x > y)
            print(json.dumps({
                'metric': 'fanout_table_sign_test',
                'sessions': s,
                'watchers': wn,
                'rounds': len(paired),
                'wins': wins,
                'losses': losses,
                'mean_delta_pct': round(sum(deltas)
                                        / max(1, len(deltas)), 1),
                'sign_p': round(sign_test_p(wins, losses), 4),
            }), flush=True)
    # absolute cells: the null-transport family above isolates
    # dispatch cost but its driver is Python (client_capped); these
    # push REAL notifications through real sockets — every session
    # holds a watch, the loadgen's writer fires, and the cell times
    # mutation -> all-notifications-on-the-wire per round
    from zkstream_tpu.utils import loadgen as _lg
    if _lg.mode() == 'c' and _lg.available() is not None:
        for s in sessions_sweep:
            try:
                cell = asyncio.run(_loadgen_fleet_cell(
                    1, s, duration=0, arm_watch=True,
                    fanout_sets=5))
            except Exception as e:
                print('# fanout loadgen cell %d failed: %r'
                      % (s, e), file=sys.stderr)
                continue
            if cell is None:
                break
            print('# fanout_loadgen_cell %s' % (json.dumps(cell),),
                  file=sys.stderr)


#: `bench.py --transport` sweep (the batched-syscall transport-tier
#: cell family): connections on the box x workload shape.  Real
#: kernel sockets — the thing being measured IS the syscall layer —
#: so the 10k cell needs ~2 fds per connection and clamps to the
#: process's fd limit when necessary.
TRANSPORT_SCALES = (128, 1000, 10000)
TRANSPORT_WORKLOADS = ('write', 'fanout')


def _transport_fd_clamp(conns: int) -> int:
    """Largest connection count the fd limit allows (2 fds per conn +
    headroom for the process's own files)."""
    try:
        import resource
        soft, _hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    except Exception:
        return conns
    ceiling = max(64, (soft - 128) // 2)
    return min(conns, ceiling)


async def transport_cell(conns: int, workload: str, backend: str,
                         collector=None, events: int | None = None,
                         ingress_shards: int | None = None,
                         ingress_backend: str | None = None,
                         time_arms: bool = False
                         ) -> dict:
    """One transport-tier measurement over REAL kernel sockets:
    ``conns`` raw TCP connections into one server, each holding a
    session.

    ``ingress_shards`` / ``ingress_backend`` parameterize the server's
    receive path (io/ingress.py) — ``bench.py --ingress`` pairs the
    sharded batched drain against the single-loop validator through
    this same cell, with the transport backend held at the process
    default for both arms so the delta isolates the rx direction.

    ``workload='write'``: per event every connection sends one
    pipelined EXISTS and the cell times the all-requests ->
    all-replies-received window — the reply path's corked flush is
    what the tier batches.  ``workload='fanout'``: every connection
    data-watches one hot path; per event one SET_DATA (through
    connection 0) fans a notification to every other connection via
    the watch table's shard flushes — the fanout_flush path.

    ``backend`` forces the tier ('uring' | 'mmsg' | 'asyncio' — the
    paired A/B arms); the cell scrapes
    ``zookeeper_flush_syscalls_total`` and ``zookeeper_submit_depth``
    so the syscalls-per-tick claim is measured, not asserted.

    ``time_arms`` moves the fanout workload's watcher re-arm burst
    INSIDE the timed window (the ingress pairing sets it: the
    all-watchers pipelined GET_DATA+watch burst is the cell's
    receive-heavy leg — the transport pairing keeps the legacy
    notify-only window, which contains almost no rx work)."""
    import asyncio
    import selectors
    import socket

    from zkstream_tpu.protocol.framing import PacketCodec
    from zkstream_tpu.server import ZKServer
    from zkstream_tpu.io.transport import METRIC_FLUSH_SYSCALLS, \
        METRIC_SUBMIT_DEPTH

    loop = asyncio.get_running_loop()
    srv = await ZKServer(transport=backend, collector=collector,
                         ingress_shards=ingress_shards,
                         ingress_backend=ingress_backend).start()
    resolved = ('asyncio' if srv.transport_tier is None
                else srv.transport_tier.backend)
    resolved_ingress = ('asyncio' if srv.ingress is None
                        else srv.ingress.backend)
    resolved_shards = 1 if srv.ingress is None else srv.ingress.nshards
    socks: list = []
    codecs: list = []
    inbox: dict[int, list] = {}
    sel = selectors.DefaultSelector()
    try:
        # raw non-blocking client sockets: the client side must not
        # cost an asyncio protocol per connection — the cell measures
        # the SERVER's outbound tier, the client just drains bytes
        connect_pkt = {'protocolVersion': 0, 'lastZxidSeen': 0,
                       'timeOut': 30000, 'sessionId': 0, 'passwd': b''}

        def _dial(i: int) -> None:
            s = socket.socket()
            s.setblocking(False)
            try:
                s.connect(('127.0.0.1', srv.port))
            except BlockingIOError:
                pass
            socks.append(s)
            codecs.append(PacketCodec())
            sel.register(s, selectors.EVENT_READ, i)

        async def send_all(pkt: dict, idxs=None):
            # encoded per connection so each codec's xid -> opcode
            # reply map stays correct (the bytes are identical)
            for i in (range(len(socks)) if idxs is None else idxs):
                s = socks[i]
                view = memoryview(codecs[i].encode(dict(pkt)))
                while view:
                    try:
                        n = s.send(view)
                        view = view[n:]
                    except (BlockingIOError, OSError):
                        await asyncio.sleep(0)

        async def recv_frames(need_per_conn: int, idxs=None,
                              timeout: float = 60.0):
            """Drain until every polled socket produced
            ``need_per_conn`` decoded packets; returns per-conn packet
            lists (handshake replies included on the first call).
            epoll-driven (selectors) so an idle pass costs one poll,
            not one recv per connection — the pump must not charge
            either arm O(conns) per event-loop iteration.  Packets
            for connections outside ``idxs`` land in the persistent
            inbox and seed that connection's next wait."""
            idxs = list(range(len(socks))) if idxs is None else idxs
            got: dict[int, list] = {i: inbox.pop(i, []) for i in idxs}
            pendset = {i for i in idxs
                       if len(got[i]) < need_per_conn}
            deadline = loop.time() + timeout
            while pendset:
                for key, _ev in sel.select(timeout=0):
                    i = key.data
                    try:
                        data = key.fileobj.recv(1 << 16)
                    except BlockingIOError:
                        continue
                    if not data:
                        raise ConnectionError('conn %d closed' % i)
                    pkts = codecs[i].decode(data)
                    if i in got:
                        got[i].extend(pkts)
                        if len(got[i]) >= need_per_conn:
                            pendset.discard(i)
                    else:
                        inbox.setdefault(i, []).extend(pkts)
                if loop.time() > deadline:
                    raise TimeoutError('%d conns still pending'
                                       % len(pendset))
                if pendset:
                    await asyncio.sleep(0)
            return got

        async def recv_bytes(targets: dict, timeout: float = 60.0):
            """The timed pump: count bytes per connection against
            ``targets`` (conn -> expected bytes) — every reply and
            notification frame in the timed phases has a fixed wire
            size, so tallying lengths verifies delivery without
            charging the window a Python frame decode per packet
            (which would dilute the A/B delta with equal-cost
            work)."""
            remaining = dict(targets)
            pend = len(remaining)
            deadline = loop.time() + timeout
            while pend:
                for key, _ev in sel.select(timeout=0):
                    i = key.data
                    try:
                        data = key.fileobj.recv(1 << 16)
                    except BlockingIOError:
                        continue
                    if not data:
                        raise ConnectionError('conn %d closed' % i)
                    r = remaining.get(i)
                    if r is None or r <= 0:
                        continue
                    r -= len(data)
                    remaining[i] = r
                    if r <= 0:
                        pend -= 1
                if loop.time() > deadline:
                    raise TimeoutError('%d conns still pending'
                                       % pend)
                if pend:
                    await asyncio.sleep(0)

        # dial + handshake in waves bounded by the server's listen
        # backlog, so a 10k-conn cell can't overflow the accept queue
        wave = min(conns, 512)
        done = 0
        while done < conns:
            n = min(wave, conns - done)
            for i in range(done, done + n):
                _dial(i)
            await asyncio.sleep(0)
            await send_all(connect_pkt, idxs=range(done, done + n))
            hs = await recv_frames(1, idxs=list(range(done, done + n)))
            for i, pkts in hs.items():
                assert pkts[0]['sessionId'] != 0
                codecs[i].handshaking = False
            done += n

        from zkstream_tpu.protocol.consts import CreateFlag
        srv.db.create('/hot', b'z' * 64, [], CreateFlag(0))

        if events is None:
            events = max(4, min(40, 80000 // max(conns, 1)))
        lat_ms: list[float] = []
        xid = [0]

        def req(pkt):
            xid[0] += 1
            return dict(pkt, xid=xid[0])

        async def probe_len(pkt) -> int:
            """One frame's wire size, measured on conn 0 (every timed
            frame is fixed-width: int64 zxids, constant path/data)."""
            await send_all(req(pkt), idxs=[0])
            buf = b''
            while len(buf) < 4 or \
                    len(buf) < 4 + int.from_bytes(buf[:4], 'big'):
                try:
                    buf += socks[0].recv(1 << 16)
                except BlockingIOError:
                    await asyncio.sleep(0)
            return 4 + int.from_bytes(buf[:4], 'big')

        if workload == 'write':
            reply_len = await probe_len({'opcode': 'EXISTS',
                                         'path': '/hot',
                                         'watch': False})
            for _ in range(events):
                frame = req({'opcode': 'EXISTS', 'path': '/hot',
                             'watch': False})
                t0 = loop.time()
                await send_all(frame)
                await recv_bytes({i: reply_len
                                  for i in range(len(socks))})
                lat_ms.append((loop.time() - t0) * 1000.0)
        else:
            watchers = list(range(1, len(socks)))
            arm_len = await probe_len({'opcode': 'GET_DATA',
                                       'path': '/hot',
                                       'watch': False})
            set_len = await probe_len({'opcode': 'SET_DATA',
                                       'path': '/hot',
                                       'data': b'z' * 64,
                                       'version': -1})
            notif_len = len(srv.encode_notification(
                'DATA_CHANGED', '/hot', 1))
            fan_targets = {w: notif_len for w in watchers}
            fan_targets[0] = set_len
            for ev in range(events):
                if time_arms:
                    t0 = loop.time()
                await send_all(req({'opcode': 'GET_DATA',
                                    'path': '/hot', 'watch': True}),
                               idxs=watchers)
                await recv_bytes({w: arm_len for w in watchers})
                if not time_arms:
                    t0 = loop.time()
                await send_all(req({'opcode': 'SET_DATA',
                                    'path': '/hot',
                                    'data': b'z' * 64,
                                    'version': -1}), idxs=[0])
                # each watcher: one notification; conn 0: the reply
                await recv_bytes(dict(fan_targets))
                lat_ms.append((loop.time() - t0) * 1000.0)
    finally:
        sel.close()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        await srv.stop()
        if srv.ledger is not None:
            srv.ledger.close_tick()
    p50, p99 = _percentiles(lat_ms)
    out = {'conns': conns, 'workload': workload,
           'backend': backend, 'resolved_backend': resolved,
           'ingress_backend': resolved_ingress,
           'ingress_shards': resolved_shards,
           # one Python pump paces every event: the A/B delta is the
           # measurement, the absolute rate is the client's ceiling
           'client_capped': True,
           'client_ceiling_ops_per_sec': PY_CLIENT_CEILING_OPS,
           'events': events,
           'event_ms_mean': round(sum(lat_ms) / len(lat_ms), 3),
           'event_ms_p50': round(p50, 3),
           'event_ms_p99': round(p99, 3)}
    if collector is not None:
        try:
            ctr = collector.get_collector(METRIC_FLUSH_SYSCALLS)
        except ValueError:
            ctr = None
        if ctr is not None:
            # exact series: {plane, backend} -> count
            sys_by_backend = {}
            for key in ctr.label_keys():
                labels = dict(key)
                if labels.get('plane') == 'server':
                    sys_by_backend[labels.get('backend', '?')] = \
                        ctr.value(labels)
            out['server_syscalls'] = sys_by_backend
            total = sum(sys_by_backend.values())
            out['syscalls_per_event'] = round(total / max(1, events), 2)
        try:
            dep = collector.get_collector(METRIC_SUBMIT_DEPTH)
        except ValueError:
            dep = None
        if dep is not None and resolved != 'asyncio':
            labels = {'plane': 'server', 'backend': resolved}
            n = dep.count(labels)
            if n:
                out['submit_depth'] = {
                    'submissions': n,
                    'mean': round(dep.sum(labels) / n, 1),
                    'p99': round(dep.percentile(99, labels), 1)}
        # the rx direction: receive submissions by backend + drain
        # depth (io/ingress.py) — syscalls-per-tick accounted BOTH
        # ways per cell
        from zkstream_tpu.io.ingress import scrape_recv_cells
        out.update(scrape_recv_cells(collector))
        from zkstream_tpu.utils.metrics import scrape_tick_cells
        tick = scrape_tick_cells(collector)
        if tick:
            out['tick_ledger'] = tick
    return out


def bench_transport() -> None:
    """The batched-syscall transport envelope (`make bench-transport`):
    paired batched-vs-asyncio cells over the conns x workload sweep
    (128/1k/10k x write-heavy/fanout), per-round adjacent A/B runs,
    exact two-sided sign test on the per-event latency — the PROFILE.md
    methodology, same as the cork/WAL/fan-out families.  The syscall
    reduction is printed per cell from
    ``zookeeper_flush_syscalls_total`` (O(dirty conns) -> O(1) per
    tick on the uring path).  Scale with ZKSTREAM_BENCH_TRANSPORT_ROUNDS;
    narrow with ``--conns`` / ``--workloads`` comma-lists."""
    import asyncio

    from zkstream_tpu.io.transport import probe
    from zkstream_tpu.utils.metrics import Collector, sign_test_p

    p = probe()
    batched = 'uring' if p.uring else ('mmsg' if p.mmsg else None)
    if batched is None:
        print('# no batched transport backend available on this '
              'platform (uring: %s; mmsg: %s) — nothing to pair'
              % (p.uring_reason, p.mmsg_reason), file=sys.stderr)
        return
    print('# transport probe: %s (pairing %s vs asyncio)'
          % (p, batched), file=sys.stderr)
    conns_sweep = _arg_ints('--conns') or list(TRANSPORT_SCALES)
    workloads = TRANSPORT_WORKLOADS
    if '--workloads' in sys.argv:
        idx = sys.argv.index('--workloads')
        if idx + 1 < len(sys.argv):
            workloads = tuple(w for w in sys.argv[idx + 1].split(',')
                              if w)
    rounds = int(os.environ.get('ZKSTREAM_BENCH_TRANSPORT_ROUNDS',
                                '10'))
    rows: dict = {}
    cells: dict = {}
    for rnd in range(rounds):
        for conns in conns_sweep:
            clamped = _transport_fd_clamp(conns)
            if clamped < conns:
                if rnd == 0:
                    print('# transport cell %d clamped to %d conns '
                          '(fd limit)' % (conns, clamped),
                          file=sys.stderr)
            for wl in workloads:
                pair = {}
                for backend in (batched, 'asyncio'):
                    col = Collector()
                    try:
                        pair[backend] = asyncio.run(transport_cell(
                            clamped, wl, backend, collector=col))
                    except Exception as e:
                        print('# transport cell %dx%s %s round '
                              'failed: %r' % (clamped, wl, backend, e),
                              file=sys.stderr)
                for backend, r in pair.items():
                    key = (conns, wl, backend)
                    if len(pair) == 2:
                        rows.setdefault(key, []).append(
                            r['event_ms_mean'])
                    if key not in cells or r['event_ms_mean'] < \
                            cells[key]['event_ms_mean']:
                        cells[key] = r
    for key in sorted(cells, key=str):
        print('# transport_cell %s' % json.dumps(cells[key]),
              file=sys.stderr)
    for conns in conns_sweep:
        for wl in workloads:
            a = rows.get((conns, wl, batched), [])
            b = rows.get((conns, wl, 'asyncio'), [])
            if not a or not b:
                continue
            paired = list(zip(a, b))
            # positive delta = batched faster (lower latency)
            deltas = [(y - x) / y * 100.0 for x, y in paired if y]
            wins = sum(1 for x, y in paired if x < y)
            losses = sum(1 for x, y in paired if x > y)
            print(json.dumps({
                'metric': 'transport_backend_sign_test',
                'conns': conns,
                'workload': wl,
                'backend': batched,
                'rounds': len(paired),
                'wins': wins,
                'losses': losses,
                'mean_delta_pct': round(sum(deltas)
                                        / max(1, len(deltas)), 1),
                'sign_p': round(sign_test_p(wins, losses), 4),
            }), flush=True)


#: `bench.py --ingress` sweep (the shared-nothing ingress cell
#: family): connections x workload, multi-shard batched drain vs the
#: single-loop validator.  Real kernel sockets (the thing measured IS
#: the receive path); the 10k/100k cells clamp to the fd limit.
INGRESS_SCALES = (1000, 10000, 100000)
INGRESS_WORKLOADS = ('write', 'fanout')


def bench_ingress() -> None:
    """The shared-nothing ingress envelope (`make bench-ingress`):
    paired multi-shard vs single-loop cells over the conns x workload
    sweep (1k/10k/100k x write-heavy/fanout), per-round adjacent A/B
    runs, exact two-sided sign test on the per-event latency — the
    PROFILE.md methodology, same as the cork/WAL/fan-out/transport
    families.  Syscalls-per-tick are printed per cell in BOTH
    directions: tx from ``zookeeper_flush_syscalls_total``, rx from
    ``zookeeper_recv_syscalls_total`` + ``zookeeper_recv_drain_depth``
    (drain submissions are O(dirty shards) per tick on the batched
    tier; the per-fd recv count inside the one C call stays O(dirty
    conns) until the uring arm — re-measured on a >= 5.1 kernel).
    Both arms run the same transport backend (the process default) so
    the delta isolates the receive direction.  Scale with
    ZKSTREAM_BENCH_INGRESS_ROUNDS; narrow with ``--conns`` /
    ``--workloads`` comma-lists."""
    import asyncio

    from zkstream_tpu.io.ingress import probe, shards_default
    from zkstream_tpu.utils.metrics import Collector, sign_test_p

    p = probe()
    batched = 'uring' if p.uring else ('mmsg' if p.mmsg else None)
    if batched is None:
        print('# no batched ingress backend available on this '
              'platform (uring: %s; mmsg: %s) — nothing to pair'
              % (p.uring_reason, p.mmsg_reason), file=sys.stderr)
        return
    shards = shards_default()
    if shards < 2:
        shards = 2      # a 1-core box still pairs sharded vs single
    print('# ingress probe: %s (pairing %d-shard %s vs single-loop)'
          % (p, shards, batched), file=sys.stderr)
    conns_sweep = _arg_ints('--conns') or list(INGRESS_SCALES)
    workloads = INGRESS_WORKLOADS
    if '--workloads' in sys.argv:
        idx = sys.argv.index('--workloads')
        if idx + 1 < len(sys.argv):
            workloads = tuple(w for w in sys.argv[idx + 1].split(',')
                              if w)
    rounds = int(os.environ.get('ZKSTREAM_BENCH_INGRESS_ROUNDS',
                                '10'))
    # both arms ride the SAME (default) transport backend: the A/B
    # delta must isolate the receive direction
    from zkstream_tpu.io.transport import backend_default
    txb = backend_default()
    #: (arm label) -> (ingress_shards, ingress_backend) cell args
    arms = {'sharded': (shards, batched), 'single': (1, 'asyncio')}
    rows: dict = {}
    cells: dict = {}
    for rnd in range(rounds):
        #: (clamped width, workload) -> measured pair: two nominal
        #: scales clamping to the SAME width (10k and 100k on a 20k
        #: fd limit) are one measurement, not two — the duplicate
        #: row reuses it instead of burning a full re-run per round
        measured: dict = {}
        for conns in conns_sweep:
            clamped = _transport_fd_clamp(conns)
            if clamped < conns and rnd == 0:
                print('# ingress cell %d clamped to %d conns '
                      '(fd limit)' % (conns, clamped),
                      file=sys.stderr)
            for wl in workloads:
                pair = measured.get((clamped, wl))
                if pair is None:
                    pair = {}
                    for arm, (ns, ib) in arms.items():
                        col = Collector()
                        try:
                            pair[arm] = asyncio.run(transport_cell(
                                clamped, wl, txb,
                                collector=col, ingress_shards=ns,
                                ingress_backend=ib, time_arms=True))
                        except Exception as e:
                            print('# ingress cell %dx%s %s round '
                                  'failed: %r'
                                  % (clamped, wl, arm, e),
                                  file=sys.stderr)
                    measured[(clamped, wl)] = pair
                for arm, r in pair.items():
                    key = (conns, wl, arm)
                    if len(pair) == 2:
                        rows.setdefault(key, []).append(
                            r['event_ms_mean'])
                    if key not in cells or r['event_ms_mean'] < \
                            cells[key]['event_ms_mean']:
                        cells[key] = dict(r, arm=arm)
    for key in sorted(cells, key=str):
        print('# ingress_cell %s' % json.dumps(cells[key]),
              file=sys.stderr)
    for conns in conns_sweep:
        for wl in workloads:
            a = rows.get((conns, wl, 'sharded'), [])
            b = rows.get((conns, wl, 'single'), [])
            if not a or not b:
                continue
            paired = list(zip(a, b))
            # positive delta = sharded faster (lower latency)
            deltas = [(y - x) / y * 100.0 for x, y in paired if y]
            wins = sum(1 for x, y in paired if x < y)
            losses = sum(1 for x, y in paired if x > y)
            print(json.dumps({
                'metric': 'ingress_shards_sign_test',
                'conns': conns,
                'workload': wl,
                'shards': shards,
                'ingress_backend': batched,
                'rounds': len(paired),
                'wins': wins,
                'losses': losses,
                'mean_delta_pct': round(sum(deltas)
                                        / max(1, len(deltas)), 1),
                'sign_p': round(sign_test_p(wins, losses), 4),
            }), flush=True)
    # absolute cells: the paired family above is paced by one
    # in-process Python pump (client_capped in its JSON); these
    # re-measure the same widths with the C loadgen driving a real
    # leader process — write-heavy steady load plus the unpaced
    # handshake wave, the numbers the ingress tier is actually for
    from zkstream_tpu.utils import loadgen as _lg
    if _lg.mode() == 'c' and _lg.available() is not None:
        for conns in conns_sweep:
            try:
                cell = asyncio.run(_loadgen_fleet_cell(
                    1, conns, duration=2.0, mix='set=100'))
            except Exception as e:
                print('# ingress loadgen cell %d failed: %r'
                      % (conns, e), file=sys.stderr)
                continue
            if cell is None:
                break
            print('# ingress_loadgen_cell %s' % (json.dumps(cell),),
                  file=sys.stderr)


#: `bench.py --read` (`make bench-read`): read-serving member counts
#: (1 = the leader alone; 3/5 = leader + 2/4 OBSERVERS — non-voting
#: read replicas, so the write quorum stays a single member across
#: every cell and only read capacity varies), session sweep and
#: workloads.  Members are REAL OS processes (server/election.py
#: ProcMember + member_worker --observer): in-process members share
#: one event loop and could never show read scale-out.
READ_MEMBERS = (1, 3, 5)
READ_SESSIONS = (1000, 10000)
READ_WORKLOADS = ('read', 'mixed')
READ_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           'tools', 'read_worker.py')


async def _read_cell(members: int, sessions: int, workload: str,
                     duration_s: float, cached: bool = False) -> dict:
    """One read-plane cell: spawn 1 voter + (members-1) observer
    processes, park ``sessions`` raw-socket read sessions across them
    (reader worker processes, tools/read_worker.py), pipeline
    GET_DATA for ``duration_s`` and sum the replies; the ``mixed``
    workload concurrently drives sets through the leader and records
    per-write latency.  Scrapes the zxid read-gate counters and the
    leader's tick-ledger phase rows after the window."""
    import shutil
    import subprocess
    import tempfile

    from zkstream_tpu import Client
    from zkstream_tpu.server.election import (
        ProcMember,
        _scrape_mntr,
        allocate_ports,
        find_leader,
    )

    import asyncio

    root = tempfile.mkdtemp(prefix='zkbench-read-')
    ports = allocate_ports(2 * members)
    fleet = [ProcMember(i, os.path.join(root, 'm%d' % i),
                        ports[2 * i], ports[2 * i + 1],
                        observer=i > 0)
             for i in range(members)]
    procs: list = []
    c = None
    loop = asyncio.get_running_loop()
    try:
        for m in fleet:
            m.spawn(fleet)
        for m in fleet:
            await m.wait_ready()
        await find_leader(fleet, min_epoch=1)
        # a generous session: at 10k sessions x 1 member the
        # handshake storm can starve pings for seconds — the cell
        # must still report its (honest, terrible) number
        c = Client(servers=[('127.0.0.1', fleet[0].client_port)],
                   shuffle_backends=False, session_timeout=120000,
                   op_timeout=60000)
        c.start()
        await c.wait_connected(timeout=20)
        await c.create('/bench', b'x' * 128)

        # driver arm: the C loadgen (tools/loadgen.c) by default —
        # one process, epoll threads, streaming decode — with the
        # Python read_worker pool kept as the ZKSTREAM_LOADGEN=py
        # validator arm (parity-checked in tests/test_loadgen.py).
        # Both speak the same READY/GO stdio protocol.
        from zkstream_tpu.utils import loadgen as lg
        lg_cmd = None
        if lg.mode() == 'c':
            lg_cmd = lg.argv(
                [('127.0.0.1', m.client_port) for m in fleet],
                sessions, duration=duration_s, mix='get=100',
                path='/bench', stdio_sync=True,
                session_timeout_ms=120000, close_sessions=True,
                ensure_path=False, cached=cached)
            if lg_cmd is None:
                print('# C loadgen unavailable (no compiler?); '
                      'falling back to the Python worker arm',
                      file=sys.stderr)
        if cached and lg_cmd is None:
            raise RuntimeError('cached read arm needs the C loadgen')
        driver = 'c' if lg_cmd is not None else 'py'
        nworkers = 0
        if driver == 'c':
            procs.append(subprocess.Popen(
                lg_cmd, stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True))
        else:
            nworkers = max(1, min(8, (os.cpu_count() or 2)
                                  - members))
            per = sessions // nworkers
            addrs = ','.join('127.0.0.1:%d' % (m.client_port,)
                             for m in fleet)
            for w in range(nworkers):
                n = per + (sessions - per * nworkers
                           if w == 0 else 0)
                procs.append(subprocess.Popen(
                    [sys.executable, READ_WORKER, addrs, str(n),
                     '%g' % (duration_s,)],
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    stderr=subprocess.DEVNULL, text=True))
        connected = 0
        for p in procs:
            line = await asyncio.wait_for(
                loop.run_in_executor(None, p.stdout.readline), 180)
            assert line.startswith('READY'), line
            connected += int(line.split()[1])
        t0 = loop.time()
        for p in procs:
            p.stdin.write('GO\n')
            p.stdin.flush()
        write_lat: list[float] = []
        seq = 0
        if workload == 'mixed':
            while loop.time() - t0 < duration_s:
                w0 = loop.time()
                await c.set('/bench', b'y%07d' % (seq,) + b'x' * 120,
                            version=-1)
                write_lat.append((loop.time() - w0) * 1000.0)
                seq += 1
        outs = []
        for p in procs:
            line = await asyncio.wait_for(
                loop.run_in_executor(None, p.stdout.readline),
                duration_s + 120)
            outs.append(json.loads(line))
            p.wait()
        if driver == 'c':
            summary = outs[0]
            reads = summary['window']['ops']
        else:
            reads = sum(o['reads'] for o in outs)
        # quiet-phase write burst: the read window is over, so this
        # isolates what ATTACHING OBSERVERS costs a write (replication
        # pushes to N mirrors) from where the read load happened to
        # land — the apples-to-apples series the write-p50 sign test
        # compares across member counts
        qlat: list[float] = []
        for i in range(200):
            w0 = loop.time()
            await c.set('/bench', b'q%07d' % (i,) + b'x' * 120,
                        version=-1)
            qlat.append((loop.time() - w0) * 1000.0)
        qlat.sort()
        cell = {
            'members': members, 'sessions': connected,
            'workload': workload, 'driver': driver,
        }
        if driver == 'c':
            cell['client_capped'] = False
            cell['read'] = {
                'ops_per_sec': summary['window']['ops_per_sec']}
            # server_ops_per_sec is the wire rate the SERVER saw: for
            # the cached arm local hits never cross the wire, so only
            # the invalidation-driven refills count against it
            cache = summary.get('cache')
            if cache is not None:
                secs = summary['window']['secs']
                cell['cache'] = cache
                cell['read']['server_ops_per_sec'] = round(
                    cache['wire_reads_win'] / secs, 1) if secs else 0.0
                cell['read']['local_hits_per_sec'] = cache.get(
                    'hits_per_sec', 0.0)
            else:
                cell['read']['server_ops_per_sec'] = (
                    summary['window']['ops_per_sec'])
            cell['reader_errors'] = (
                sum(v['errors'] for v in summary['ops'].values())
                + summary['errors']['io']
                + summary['errors']['proto'])
            cell['zxid'] = summary['zxid']
            cell['handshake'] = summary.get('handshake')
            cell['loadgen_rc'] = procs[0].returncode
        else:
            # the Python arm is the validator: its absolute rate is
            # the client pool's decode ceiling, not the server's
            cell['client_capped'] = True
            cell['client_ceiling'] = {
                'workers': nworkers,
                'per_worker_ops_per_sec': round(
                    reads / duration_s / max(1, nworkers), 1),
                'decode_ceiling_ops_per_sec':
                    PY_CLIENT_CEILING_OPS}
            cell['read'] = {
                'ops_per_sec': round(reads / duration_s, 1)}
            cell['reader_errors'] = sum(o['errors'] for o in outs)
        if write_lat:
            lat = sorted(write_lat)
            cell['write'] = {
                'ops_per_sec': round(len(lat) / duration_s, 1),
                'p50_ms': round(lat[len(lat) // 2], 3),
                'p99_ms': round(lat[min(len(lat) - 1,
                                        int(len(lat) * 0.99))], 3),
            }
        cell['write_quiet'] = {
            'p50_ms': round(qlat[len(qlat) // 2], 3),
            'p99_ms': round(qlat[min(len(qlat) - 1,
                                     int(len(qlat) * 0.99))], 3),
        }
        blocks = bounces = 0
        for m in fleet:
            try:
                rows = await _scrape_mntr(m.client_port)
            except (OSError, TimeoutError):
                continue
            blocks += int(rows.get('zk_read_zxid_gate_blocks', 0))
            bounces += int(rows.get('zk_read_zxid_gate_bounces', 0))
            if m is fleet[0]:
                cell['tick_phases'] = {
                    k.split('"')[1]: float(v)
                    for k, v in rows.items()
                    if k.startswith('zk_tick_phase_ms_p99')}
        cell['gate'] = {'blocks': blocks, 'bounces': bounces}
        return cell
    finally:
        if c is not None:
            try:
                await asyncio.wait_for(c.close(), 5)
            except Exception:
                c.pool.stop()
        for p in procs:
            if p.poll() is None:
                p.kill()
            try:
                p.stdout.close()
                p.stdin.close()
            except Exception:
                pass
        for m in fleet:
            try:
                m.kill()
            except Exception:
                pass
        shutil.rmtree(root, ignore_errors=True)


def _proc_stats(pid: int) -> dict:
    """RSS + open-fd count of one process, from /proc."""
    out: dict = {}
    try:
        with open('/proc/%d/status' % pid) as f:
            for ln in f:
                if ln.startswith('VmRSS:'):
                    out['rss_mb'] = round(
                        int(ln.split()[1]) / 1024.0, 1)
                    break
        out['fds'] = len(os.listdir('/proc/%d/fd' % pid))
    except OSError:
        pass
    return out


async def _loadgen_fleet_cell(members: int, sessions: int, *,
                              duration=None, mix=None, ramp=None,
                              idle_ping=None, arm_watch=False,
                              fanout_sets=None,
                              setwatches_storm=False,
                              pipeline=None) -> dict | None:
    """One ABSOLUTE (non-paired) cell: a real leader + observers
    fleet driven by the C loadgen.  The loadgen's READY/GO stdio sync
    lets us scrape every member's RSS and fd count at the
    all-sessions-connected peak before the load window opens.
    Returns the loadgen summary annotated with the fleet shape, or
    None when the binary can't be built (no compiler)."""
    import shutil
    import subprocess
    import tempfile

    from zkstream_tpu.server.election import (
        ProcMember,
        allocate_ports,
        find_leader,
    )
    from zkstream_tpu.utils import loadgen as lg

    import asyncio

    if lg.available() is None:   # build before spawning the fleet
        return None
    loop = asyncio.get_running_loop()
    root = tempfile.mkdtemp(prefix='zkbench-lg-')
    ports = allocate_ports(2 * members)
    # each member sees ~sessions/members connections (round-robin);
    # tell it so it can lift its fd limit before the wave hits, and
    # lift the overload plane's admission cap (default 4096, a
    # production defense) to the same budget — the campaign measures
    # the HOST's fd ceiling, not the admission knob's default
    need = -(-sessions // members) + 1024
    os.environ['ZKSTREAM_MEMBER_FDS'] = str(need)
    os.environ['ZKSTREAM_MAX_CONNS'] = str(need)
    fleet = [ProcMember(i, os.path.join(root, 'm%d' % i),
                        ports[2 * i], ports[2 * i + 1],
                        observer=i > 0)
             for i in range(members)]
    proc = None
    try:
        for m in fleet:
            m.spawn(fleet)
        for m in fleet:
            await m.wait_ready()
        await find_leader(fleet, min_epoch=1)
        # the session timeout must cover the WHOLE connect wave: no
        # pings flow while a thread is still handshaking, and this
        # host's single-core accept path sustains ~1.5k handshakes/s
        # — a fixed 120 s timeout would expire the first sessions of
        # any wave past ~180k before the last one connects
        st_ms = max(120000, int(sessions * 1.5))
        cmd = lg.argv(
            [('127.0.0.1', m.client_port) for m in fleet],
            sessions, duration=duration, mix=mix, ramp=ramp,
            idle_ping=idle_ping, arm_watch=arm_watch,
            fanout_sets=fanout_sets,
            setwatches_storm=setwatches_storm, pipeline=pipeline,
            stdio_sync=True, session_timeout_ms=st_ms)
        proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        ready_s = 120.0 + sessions / 500.0
        line = await asyncio.wait_for(
            loop.run_in_executor(None, proc.stdout.readline),
            ready_s)
        assert line.startswith('READY'), line
        connected = int(line.split()[1])
        peak = [dict(_proc_stats(m.proc.pid),
                     member=m.member_id, observer=m.observer)
                for m in fleet if m.proc is not None]
        proc.stdin.write('GO\n')
        proc.stdin.flush()
        win_s = (300.0 + (duration or 0.0)
                 + sessions / 500.0
                 + (60.0 if fanout_sets else 0.0)
                 + (60.0 if setwatches_storm else 0.0))
        line = await asyncio.wait_for(
            loop.run_in_executor(None, proc.stdout.readline),
            win_s)
        proc.wait()
        cell = dict(json.loads(line), members=members, driver='c',
                    rc=proc.returncode)
        cell['connected'] = connected
        cell['members_at_peak'] = peak
        return cell
    finally:
        os.environ.pop('ZKSTREAM_MEMBER_FDS', None)
        os.environ.pop('ZKSTREAM_MAX_CONNS', None)
        if proc is not None and proc.poll() is None:
            proc.kill()
        if proc is not None:
            try:
                proc.stdout.close()
                proc.stdin.close()
            except Exception:
                pass
        for m in fleet:
            try:
                m.kill()
            except Exception:
                pass
        shutil.rmtree(root, ignore_errors=True)


def bench_million() -> None:
    """The million-session campaign (`make bench-million`): ONE
    loadgen run per member count against a real leader + observers
    fleet — handshake wave (optionally paced with
    ZKSTREAM_BENCH_MILLION_RAMP handshakes/s), keepalive-only hold
    window with live pings, a watch armed per session, fan-out
    rounds through every armed watcher, and a post-failover-shaped
    SET_WATCHES storm.  Member RSS and fd counts are scraped at the
    all-connected peak; when the host fd/memory cap (not the server)
    bounds the session count, the cell says so by name in
    ``caps.binding_constraint``.

    The default is tier-1-safe (2000 sessions x 2s); the real
    campaign (PROFILE.md round 19) scales with
    ZKSTREAM_BENCH_MILLION_SESSIONS=1000000,
    ZKSTREAM_BENCH_MILLION_MEMBERS=3 (comma-list),
    ZKSTREAM_BENCH_MILLION_SECS and ZKSTREAM_BENCH_MILLION_RAMP."""
    import asyncio

    from zkstream_tpu.utils import loadgen as lg

    if lg.mode() != 'c' or lg.available() is None:
        print('# bench-million needs the C loadgen (no compiler or '
              'ZKSTREAM_LOADGEN=py); nothing to run',
              file=sys.stderr)
        return
    env = os.environ.get
    sessions = int(env('ZKSTREAM_BENCH_MILLION_SESSIONS', '2000'))
    member_list = [int(x) for x in
                   env('ZKSTREAM_BENCH_MILLION_MEMBERS',
                       '3').split(',') if x]
    secs = float(env('ZKSTREAM_BENCH_MILLION_SECS', '2'))
    ramp = float(env('ZKSTREAM_BENCH_MILLION_RAMP', '0'))
    for members in member_list:
        try:
            cell = asyncio.run(_loadgen_fleet_cell(
                members, sessions, duration=secs,
                ramp=ramp if ramp > 0 else None,
                idle_ping=max(1.0, secs / 2.0),
                arm_watch=True, fanout_sets=3,
                setwatches_storm=True, pipeline=1))
        except Exception as e:
            print('# million cell m=%d s=%d failed: %r'
                  % (members, sessions, e), file=sys.stderr)
            continue
        if cell is None:
            return
        print('# million_cell %s' % (json.dumps(cell),),
              file=sys.stderr)


def bench_read() -> None:
    """The read scale-out envelope (`make bench-read`; README "Read
    plane"): paired cells at 1 vs 3 vs 5 read-serving members (leader
    + observers, real OS processes) x session sweep x read-heavy /
    mixed workloads.  Acceptance: read throughput significantly
    HIGHER at 3 and 5 members than 1 on the read-heavy cells (exact
    sign test over per-round adjacent runs), and write p50 NOT
    significantly worse with observers attached (the quorum never
    widened: observers don't vote).  Rounds via
    ZKSTREAM_BENCH_READ_ROUNDS; window via ZKSTREAM_BENCH_READ_SECS;
    narrow with --sessions / --workloads.  Table in PROFILE.md "Read
    plane"."""
    import asyncio as aio

    from zkstream_tpu.utils.metrics import sign_test_p

    rounds = int(os.environ.get('ZKSTREAM_BENCH_READ_ROUNDS', '8'))
    duration = float(os.environ.get('ZKSTREAM_BENCH_READ_SECS',
                                    '2.0'))
    sessions_sweep = _arg_ints('--sessions') or list(READ_SESSIONS)
    workloads = list(READ_WORKLOADS)
    if '--workloads' in sys.argv:
        idx = sys.argv.index('--workloads')
        workloads = sys.argv[idx + 1].split(',')
    env_sessions = os.environ.get('ZKSTREAM_BENCH_READ_SESSIONS')
    if env_sessions:
        sessions_sweep = [int(x) for x in env_sessions.split(',')]

    reads: dict = {}
    writes: dict = {}
    cells: dict = {}
    for _rnd in range(rounds):
        for sessions in sessions_sweep:
            for wl in workloads:
                for n in READ_MEMBERS:
                    key = (sessions, wl, n)
                    try:
                        r = aio.run(_read_cell(n, sessions, wl,
                                               duration))
                    except Exception as e:
                        print('# read cell m=%d s=%d %s failed: %r'
                              % (n, sessions, wl, e),
                              file=sys.stderr)
                        # placeholder keeps the per-round pairing
                        # aligned: sign() drops pairs with a None
                        reads.setdefault(key, []).append(None)
                        writes.setdefault(key, []).append(None)
                        continue
                    reads.setdefault(key, []).append(
                        r['read']['ops_per_sec'])
                    writes.setdefault(key, []).append(
                        r['write_quiet']['p50_ms'])
                    if key not in cells or r['read']['ops_per_sec'] \
                            > cells[key]['read']['ops_per_sec']:
                        cells[key] = r
    for key in sorted(cells):
        print('# read_cell %s' % (json.dumps(cells[key]),),
              file=sys.stderr)

    def sign(metric: str, rows: dict, sessions: int, wl: str,
             n: int, higher_is_better: bool) -> None:
        a = rows.get((sessions, wl, n), [])
        b = rows.get((sessions, wl, 1), [])
        paired = [(x, y) for x, y in zip(a, b)
                  if x is not None and y is not None]
        if not paired:
            return
        deltas = [(x - y) / y * 100.0 for x, y in paired if y]
        wins = sum(1 for x, y in paired
                   if (x > y) == higher_is_better and x != y)
        losses = sum(1 for x, y in paired
                     if (x > y) != higher_is_better and x != y)
        print(json.dumps({
            'metric': metric,
            'pair': '%d-vs-1' % (n,),
            'sessions': sessions,
            'workload': wl,
            'rounds': len(paired),
            'wins': wins,
            'losses': losses,
            'mean_delta_pct': round(sum(deltas)
                                    / max(1, len(deltas)), 1),
            'sign_p': round(sign_test_p(wins, losses), 4),
        }), flush=True)

    for sessions in sessions_sweep:
        for wl in workloads:
            for n in READ_MEMBERS[1:]:
                sign('read_scaleout_sign_test', reads, sessions, wl,
                     n, higher_is_better=True)
                # quiet-phase write p50: LOWER is better; the bar
                # is "not significantly worse with observers
                # attached" (the quorum never widened)
                sign('read_write_p50_sign_test', writes,
                     sessions, wl, n, higher_is_better=False)

    _bench_read_cached(rounds, duration)


def _bench_read_cached(rounds: int, duration: float) -> None:
    """The cached arm of `bench.py --read` (README "Client cache
    plane"): paired uncached-vs-cached C-loadgen cells against the
    same single-member fleet shape.  The cached arm arms one
    persistent-recursive ADD_WATCH per session (io/cache.py shape)
    and serves steady reads from the local entry, so the server only
    sees invalidation-driven refill reads.  Acceptance: server-side
    read QPS reduced >= 95% on every pair (exact sign test at the
    95% bar, not at break-even) and cached p50 in single-digit
    microseconds.  Narrow with ZKSTREAM_BENCH_READ_CACHED_ROUNDS /
    _SESSIONS; table in PROFILE.md "Read plane"."""
    import asyncio as aio

    from zkstream_tpu.utils import loadgen as lg
    from zkstream_tpu.utils.metrics import sign_test_p

    if lg.mode() != 'c' or lg.available() is None:
        print('# cached read arm needs the C loadgen (no compiler '
              'or ZKSTREAM_LOADGEN=py); skipped', file=sys.stderr)
        return
    rounds = int(os.environ.get('ZKSTREAM_BENCH_READ_CACHED_ROUNDS',
                                str(rounds)))
    sessions = int(os.environ.get(
        'ZKSTREAM_BENCH_READ_CACHED_SESSIONS', '100'))
    pairs: list[tuple[dict, dict]] = []
    best: dict = {}
    for _rnd in range(rounds):
        row: dict = {}
        for cached in (False, True):
            arm = 'cached' if cached else 'uncached'
            try:
                r = aio.run(_read_cell(1, sessions, 'read', duration,
                                       cached=cached))
            except Exception as e:
                print('# cached read cell %s s=%d failed: %r'
                      % (arm, sessions, e), file=sys.stderr)
                row = {}
                break
            row[arm] = r
            if arm not in best or (r['read']['ops_per_sec']
                                   > best[arm]['read']['ops_per_sec']):
                best[arm] = r
        if row:
            pairs.append((row['uncached'], row['cached']))
    for arm in sorted(best):
        print('# read_cached_cell %s'
              % (json.dumps(dict(best[arm], arm=arm)),),
              file=sys.stderr)
    if not pairs:
        return
    # exact sign test AT THE 95% BAR: a pair only counts as a win
    # when the cached arm's server-side read rate is below 5% of the
    # uncached arm's — break-even or a mere improvement is a loss
    wins = losses = 0
    reductions: list[float] = []
    p50s: list[float] = []
    for u, cc in pairs:
        uq = u['read']['server_ops_per_sec']
        cq = cc['read']['server_ops_per_sec']
        if uq > 0:
            reductions.append((uq - cq) / uq * 100.0)
        if cq < uq * 0.05:
            wins += 1
        else:
            losses += 1
        p50s.append(cc['cache']['hit_p50_us'])
    print(json.dumps({
        'metric': 'read_cached_qps_reduction_sign_test',
        'pair': 'cached-vs-uncached',
        'bar': 'server read QPS reduced >= 95%',
        'sessions': sessions,
        'rounds': len(pairs),
        'wins': wins,
        'losses': losses,
        'mean_reduction_pct': round(
            sum(reductions) / max(1, len(reductions)), 2),
        'cached_hit_p50_us': round(
            sorted(p50s)[len(p50s) // 2], 3),
        'sign_p': round(sign_test_p(wins, losses), 4),
    }), flush=True)


def _guard_backend(timeout_s: float | None = None) -> None:
    """Probe the default JAX backend in a SUBPROCESS before this
    process touches jax: a wedged tunneled-TPU backend has been
    observed to block device enumeration for 20+ minutes and then
    fail, which would kill the run before the flagship metric prints.
    If the probe cannot enumerate devices, fall back to the host CPU
    backend so the benchmark completes (the numbers then measure the
    CPU backend and say so).

    A timed-out probe gets ONE retry: the tunnel has been observed
    flaky rather than dead (first enumeration hangs past the budget
    while a fresh attempt succeeds in under a minute), and a retry is
    the difference between the round's flagship landing on the chip
    versus the CPU fallback.  A probe that *fails* (nonzero exit) is
    not retried — backend setup errors are deterministic.

    The probe pays one extra backend spin-up on a healthy run — the
    price of a guaranteed headline when the tunnel is wedged; set
    ZKSTREAM_BENCH_NO_PROBE=1 to skip it, or
    ZKSTREAM_BENCH_PROBE_TIMEOUT=<seconds> to resize the per-attempt
    budget (default 240).  The probe subprocess mechanics (own
    session, group kill on timeout, no pipes) live in
    platform.bounded_probe, shared with tools/tpu_window.py."""
    import os

    from zkstream_tpu.utils.platform import bounded_probe

    if os.environ.get('ZKSTREAM_BENCH_NO_PROBE') == '1':
        return
    if timeout_s is None:
        raw = os.environ.get('ZKSTREAM_BENCH_PROBE_TIMEOUT')
        try:
            timeout_s = float(raw) if raw else 240.0
        except ValueError:
            timeout_s = -1.0      # rejected below
        if not 0 < timeout_s < float('inf'):  # also rejects nan
            print('# ignoring invalid ZKSTREAM_BENCH_PROBE_TIMEOUT'
                  '=%r; using 240s' % (raw,), file=sys.stderr)
            timeout_s = 240.0
    reason = None
    for attempt in range(2):
        status, detail, _rc = bounded_probe(
            'import jax; jax.devices()', timeout_s)
        if status == 'ok':
            return
        if status == 'timeout':
            reason = 'probe timed out after %.0fs (%d attempts)' \
                % (timeout_s, attempt + 1)
            continue
        if status == 'killed':
            # signal-killed: environmental (OOM killer, tunnel-side
            # abort), retried like a timeout — not a deterministic
            # backend setup error
            reason = 'probe killed by a signal (%s, %d attempts)' \
                % (detail or '?', attempt + 1)
            continue
        reason = 'probe failed: %s' % (detail or '?')
        break
    print('# default JAX backend unavailable (%s); falling back to '
          'the host CPU backend' % (reason,), file=sys.stderr)
    from zkstream_tpu.utils.platform import force_cpu
    force_cpu(n_devices=1)


def main() -> None:
    if '--wal' in sys.argv:
        # `make bench-wal`: the paired durability-plane cell family
        # (wal-off vs sync=tick vs sync=always, write-heavy).  Host-
        # path only, same rationale as --write.
        from zkstream_tpu.utils.platform import force_cpu
        force_cpu(n_devices=1)
        bench_wal()
        return
    if '--election' in sys.argv:
        # `make bench-election`: the coordination-plane failover
        # family (leader kill -> elected successor, 3 vs 5 members).
        # Host-path only.
        from zkstream_tpu.utils.platform import force_cpu
        force_cpu(n_devices=1)
        bench_election()
        return
    if '--quorum' in sys.argv:
        # `make bench-quorum`: the quorum-commit cost family
        # (quorum-on/off at 3/5 members + MULTI batching cells).
        # Host-path only.
        from zkstream_tpu.utils.platform import force_cpu
        force_cpu(n_devices=1)
        bench_quorum()
        return
    if '--reconfig' in sys.argv:
        # `make bench-reconfig`: the dynamic-membership cost family
        # (steady vs during-observer-join vs during-voter-replace
        # write p50s, paired sign tests).  Host-path only.
        from zkstream_tpu.utils.platform import force_cpu
        force_cpu(n_devices=1)
        bench_reconfig()
        return
    if '--traceov' in sys.argv:
        # `make bench-trace`: the paired trace-plane overhead family
        # (server span rings + tick ledger vs
        # ZKSTREAM_NO_SERVER_TRACE=1).  Host-path only.
        from zkstream_tpu.utils.platform import force_cpu
        force_cpu(n_devices=1)
        bench_trace_overhead()
        return
    if '--blackbox' in sys.argv:
        # `make bench-blackbox`: the paired black-box-plane overhead
        # family (flight recorder + slow-op digest vs
        # ZKSTREAM_NO_BLACKBOX=1, WAL-backed write-heavy cells).
        # Host-path only.
        from zkstream_tpu.utils.platform import force_cpu
        force_cpu(n_devices=1)
        bench_blackbox_overhead()
        return
    if '--overload' in sys.argv:
        # `make bench-overload`: the overload plane's cost + defense
        # family (stalled-consumer defense cells + plane-overhead
        # cells vs ZKSTREAM_NO_OVERLOAD=1).  Host-path only.
        from zkstream_tpu.utils.platform import force_cpu
        force_cpu(n_devices=1)
        bench_overload()
        return
    if '--transport' in sys.argv:
        # `make bench-transport`: the batched-syscall transport-tier
        # cell family (io/transport.py: uring/mmsg vs the asyncio
        # validator) over real kernel sockets.  Host-path only.
        from zkstream_tpu.utils.platform import force_cpu
        force_cpu(n_devices=1)
        bench_transport()
        return
    if '--ingress' in sys.argv:
        # `make bench-ingress`: the shared-nothing ingress cell
        # family (io/ingress.py: multi-shard batched receive drain
        # vs the single-loop validator) over real kernel sockets.
        # Host-path only.
        from zkstream_tpu.utils.platform import force_cpu
        force_cpu(n_devices=1)
        bench_ingress()
        return
    if '--fanout' in sys.argv:
        # `make bench-fanout`: the serving-plane fan-out cell family
        # (sharded watch table vs per-connection emitter dispatch).
        # Host-path only; no accelerator probe, no kernel sockets.
        from zkstream_tpu.utils.platform import force_cpu
        force_cpu(n_devices=1)
        bench_fanout()
        return
    if '--read' in sys.argv:
        # `make bench-read`: the read scale-out cell family (README
        # "Read plane": 1 vs 3 vs 5 read-serving members as real OS
        # processes — leader + non-voting observers).  Host-path
        # only.
        from zkstream_tpu.utils.platform import force_cpu
        force_cpu(n_devices=1)
        bench_read()
        return
    if '--million' in sys.argv:
        # `make bench-million`: the million-session campaign (README
        # "Load generation"; PROFILE.md round 19) — handshake waves,
        # keepalive hold, per-session watches with fan-out, and a
        # SET_WATCHES storm, driven by the C loadgen against a real
        # member fleet.  Host-path only.
        from zkstream_tpu.utils.platform import force_cpu
        force_cpu(n_devices=1)
        bench_million()
        return
    if '--write' in sys.argv:
        # `make bench-write`: the write-heavy client-ops cell family
        # only — host-path, no accelerator probe, no flagship decode
        # stages (their readbacks are unrelated to the outbound
        # plane).  Pin CPU before jax initializes: a wedged tunneled
        # accelerator must not stall a host-path bench.
        from zkstream_tpu.utils.platform import force_cpu
        force_cpu(n_devices=1)
        bench_client_ops(write_heavy=True)
        return
    _guard_backend()
    # Initialize the host CPU backend FIRST: the fleet ingest's
    # latency-aware placement wants it, and creating a second PJRT
    # client after heavy accelerator use has been observed to block on
    # a tunneled TPU (the ingest guards with a timeout, but eager init
    # here makes the fast path deterministic).
    try:
        import jax
        jax.devices('cpu')
    except Exception as e:  # pragma: no cover - environment-specific
        print('# cpu backend unavailable: %s' % (e,), file=sys.stderr)

    buf, lens, streams, slots = _fleet()
    scalar = bench_scalar(streams)
    scalar_full, pkts = bench_scalar_full(streams, slots)
    ext_full = bench_ext_full(streams, slots)
    tick, full, full_deployed = bench_tensor(buf, lens, streams,
                                             pkts, slots)
    print(f'# scalar tick baseline: {scalar:.2f} MiB/s over {B} '
          f'streams x {FRAMES} frames (headers only, equal work)',
          file=sys.stderr)
    print(f'# scalar full-decode baseline: {scalar_full:.2f} MiB/s '
          f'over {SCALAR_FULL_STREAMS} streams (framing + header + '
          f'body -> packet dicts, mixed opcodes)', file=sys.stderr)
    if ext_full is not None:
        print(f'# C-extension full decode: {ext_full:.2f} MiB/s '
              f'(this framework\'s own native scalar path)',
          file=sys.stderr)
    # Roofline note: MiB/s here counts WIRE BYTES PROCESSED per
    # second, not bytes touched — the header scan gathers ~20 B and
    # the full decode ~(20 + data + Stat) B of each 104 B frame, so
    # multi-TiB/s figures are consistent with v5e's ~0.8 TB/s HBM
    # (the decode reads each wire byte at most once but is PAID per
    # frame, and most wire bytes are payload it only slices).
    print('# note: MiB/s = wire bytes processed; see roofline note '
          'in bench.py main()', file=sys.stderr)
    # protocol-tick metric (headers + routing; the r1/r2 series)
    backend = jax.default_backend()
    print(json.dumps({
        'metric': 'wire_decode_throughput',
        'value': round(tick, 2),
        'unit': 'MiB/s',
        'vs_baseline': round(tick / scalar, 3),
        'backend': backend,
    }), flush=True)
    # toy-width full decode (the r3 headline's configuration, kept for
    # series comparability)
    print(json.dumps({
        'metric': 'wire_full_decode_toy_width',
        'value': round(full, 2),
        'unit': 'MiB/s',
        'vs_baseline': round(full / scalar_full, 3),
        'widths': 'data16/path8',
        'backend': backend,
    }), flush=True)
    try:
        bench_client_ops()
    except Exception as e:  # secondary metrics never sink the run
        print('# client_ops stage failed: %r' % (e,), file=sys.stderr)
    sys.stderr.flush()
    # the flagship: FULL decode at the DEPLOYED body configuration
    # (io/ingest.py defaults: 256-byte data/path planes + children/ACL
    # list planes) vs the scalar codec doing the same complete work —
    # printed last so the driver records it as the round's headline
    # (VERDICT r3 next #2: the headline must be the number the shipped
    # configuration would produce)
    print(json.dumps({
        'metric': 'wire_full_decode_throughput',
        'value': round(full_deployed, 2),
        'unit': 'MiB/s',
        'vs_baseline': round(full_deployed / scalar_full, 3),
        'widths': 'data256/path256/ch16x64/acl4',
        'corpus': 'mixed-opcode %dx%d (data/children/acl/notif/'
                  'err/ping)' % (B, FRAMES),
        'toy_width_mibs': round(full, 2),
        'backend': backend,
    }), flush=True)


if __name__ == '__main__':
    main()
