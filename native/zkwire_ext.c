/* zkwire_ext: CPython-extension decoder for the per-connection receive
 * hot path.
 *
 * Why this exists (see tools/profile_hotpath.py for the numbers): the
 * pure-Python scalar decode of a GET_DATA reply stream runs at ~15-25
 * MiB/s, and >90% of that time is jute primitive reads — per-field
 * struct.unpack_from calls, bounds checks, and dict/dataclass plumbing
 * in zkstream_tpu/protocol/{jute,records}.py.  Framing alone is cheap
 * (the plain-C-ABI scanner in zkwire.cpp covers it), so the profitable
 * native boundary is the *whole* receive transform: accumulated bytes
 * -> list of packet dicts, in one C pass.  That is the same span the
 * reference executes per socket read (frame loop lib/zk-streams.js:
 * 39-99 + reply parse lib/zk-buffer.js:275-370), and the host-side
 * counterpart of the batched TPU pipeline (ops/pipeline.py).
 *
 * Contract (mirrors PacketCodec.decode exactly; A/B-tested in
 * tests/test_native_ext.py):
 *
 *   decode_responses(buf, xid_map, max_packet)
 *     -> (pkts, consumed, err_kind, err_msg)
 *
 * - Slices every complete length-prefixed frame out of buf[0:len];
 *   `consumed` is the byte offset the caller must drop from its
 *   accumulation buffer.
 * - Each frame decodes to the same packet dict the Python codec builds:
 *   xid/zxid/err + opcode-specific body fields (data/stat/path/children/
 *   acl/type/state), with Stat/ACL/Id constructed through the Python
 *   classes registered via setup().
 * - Bad length prefix (negative or > max_packet): err_kind BAD_LENGTH,
 *   consumed = offset of the offending prefix, pkts = [] (frames
 *   before it are consumed-and-dropped — identical to
 *   FrameDecoder.feed raising mid-scan).
 * - Undecodable frame body: err_kind BAD_DECODE, pkts = packets decoded
 *   before the bad frame (PacketCodec attaches them to the raised
 *   error), consumed = all complete frames (they left the buffer in the
 *   scalar path too).
 * - xids are popped from xid_map exactly as records.read_response does.
 *
 * Built with a bare `gcc -shared -fPIC` against the interpreter's
 * headers; loaded via utils/native.py with the same
 * version-named-artifact discipline as the C-ABI scanner.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

/* ---- registered Python objects (held forever once set) ---- */

static PyObject *g_stat_cls;    /* records.Stat */
static PyObject *g_acl_cls;    /* records.ACL */
static PyObject *g_id_cls;     /* records.Id */
static PyObject *g_perm_cls;   /* consts.Perm (IntFlag) */
static PyObject *g_create_flag_cls; /* consts.CreateFlag (IntFlag) */
static PyObject *g_err_names;  /* dict int -> str (ErrCode names) */
static PyObject *g_notif_types; /* dict int -> str */
static PyObject *g_states;     /* dict int -> str (KeeperState names) */
static PyObject *g_layouts;    /* dict opcode-str -> layout int */
static PyObject *g_req_opcodes; /* dict int -> (name, req-layout int) */
static PyObject *g_op_names;   /* dict int -> str: EVERY valid OpCode */

/* interned key + special-opcode strings */
static PyObject *s_xid, *s_zxid, *s_err, *s_opcode, *s_data, *s_stat,
    *s_path, *s_children, *s_acl, *s_type, *s_state, *s_watch,
    *s_version, *s_relZxid, *s_events, *s_flags;
static PyObject *s_notification, *s_ping, *s_auth, *s_set_watches, *s_ok;
static PyObject *s_dataChanged, *s_createdOrDestroyed,
    *s_childrenChanged;

/* layout enum — the Python side builds g_layouts with these values */
enum {
  LAYOUT_EMPTY = 0,
  LAYOUT_GET_CHILDREN = 1,
  LAYOUT_GET_CHILDREN2 = 2,
  LAYOUT_CREATE = 3,
  LAYOUT_GET_ACL = 4,
  LAYOUT_GET_DATA = 5,
  LAYOUT_STAT_ONLY = 6,
  LAYOUT_NOTIFICATION = 7,
};

/* request-body layouts (server direction) — g_req_opcodes values */
enum {
  RQ_EMPTY = 0,
  RQ_PATH = 1,
  RQ_PATH_WATCH = 2,
  RQ_CREATE = 3,
  RQ_DELETE = 4,
  RQ_SET_DATA = 5,
  RQ_SET_WATCHES = 6,
};

/* ---- byte readers (big-endian, bounds-checked) ---- */

typedef struct {
  const uint8_t *p;
  Py_ssize_t len;
  Py_ssize_t off;
  char err[192]; /* non-empty => decode error */
} Cursor;

static int need(Cursor *c, Py_ssize_t n) {
  if (c->off + n > c->len) {
    snprintf(c->err, sizeof(c->err),
             "need %zd bytes at offset %zd, have %zd", n, c->off,
             c->len - c->off);
    return 0;
  }
  return 1;
}

static int32_t rd_i32(Cursor *c) {
  const uint8_t *p = c->p + c->off;
  c->off += 4;
  return (int32_t)(((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
                   ((uint32_t)p[2] << 8) | (uint32_t)p[3]);
}

static int64_t rd_i64(Cursor *c) {
  const uint8_t *p = c->p + c->off;
  c->off += 8;
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | p[i];
  return (int64_t)v;
}

/* int-length-prefixed UTF-8 string; negative length => "" (the jute
 * empty-buffer quirk, lib/jute-buffer.js:99-100). NULL on error. */
static PyObject *rd_string(Cursor *c) {
  if (!need(c, 4)) return NULL;
  int32_t ln = rd_i32(c);
  if (ln < 0) return PyUnicode_FromStringAndSize("", 0);
  if (!need(c, ln)) return NULL;
  PyObject *s =
      PyUnicode_DecodeUTF8((const char *)c->p + c->off, ln, NULL);
  if (s == NULL) {
    /* surface as a decode error, not a raised exception */
    PyErr_Clear();
    snprintf(c->err, sizeof(c->err), "invalid utf-8 string at offset %zd",
             c->off);
    return NULL;
  }
  c->off += ln;
  return s;
}

static PyObject *rd_bytes(Cursor *c) {
  if (!need(c, 4)) return NULL;
  int32_t ln = rd_i32(c);
  if (ln < 0) return PyBytes_FromStringAndSize("", 0);
  if (!need(c, ln)) return NULL;
  PyObject *b =
      PyBytes_FromStringAndSize((const char *)c->p + c->off, ln);
  c->off += ln;
  return b;
}

/* the 68-byte Stat record in one bounds check
 * (reference: lib/zk-buffer.js:428-442).
 *
 * Stat is a NamedTuple, i.e. a tuple subclass, so the instance is
 * built through tuple's own tp_new — the exact effect of
 * `tuple.__new__(Stat, fields)` — skipping the generated Python-level
 * __new__ (which costs ~10x the tuple itself on the hot path). */
static PyObject *rd_stat(Cursor *c) {
  if (!need(c, 68)) return NULL;
  PyObject *vals = PyTuple_New(11);
  if (vals == NULL) return NULL;
#define STAT_FIELD(i, expr)                 \
  do {                                      \
    PyObject *v_ = (expr);                  \
    if (v_ == NULL) {                       \
      Py_DECREF(vals);                      \
      return NULL;                          \
    }                                       \
    PyTuple_SET_ITEM(vals, (i), v_);        \
  } while (0)
  STAT_FIELD(0, PyLong_FromLongLong(rd_i64(c)));  /* czxid */
  STAT_FIELD(1, PyLong_FromLongLong(rd_i64(c)));  /* mzxid */
  STAT_FIELD(2, PyLong_FromLongLong(rd_i64(c)));  /* ctime */
  STAT_FIELD(3, PyLong_FromLongLong(rd_i64(c)));  /* mtime */
  STAT_FIELD(4, PyLong_FromLong(rd_i32(c)));      /* version */
  STAT_FIELD(5, PyLong_FromLong(rd_i32(c)));      /* cversion */
  STAT_FIELD(6, PyLong_FromLong(rd_i32(c)));      /* aversion */
  STAT_FIELD(7, PyLong_FromLongLong(rd_i64(c)));  /* ephemeralOwner */
  STAT_FIELD(8, PyLong_FromLong(rd_i32(c)));      /* dataLength */
  STAT_FIELD(9, PyLong_FromLong(rd_i32(c)));      /* numChildren */
  STAT_FIELD(10, PyLong_FromLongLong(rd_i64(c))); /* pzxid */
#undef STAT_FIELD
  PyObject *args = PyTuple_Pack(1, vals);
  Py_DECREF(vals);
  if (args == NULL) return NULL;
  PyObject *stat =
      PyTuple_Type.tp_new((PyTypeObject *)g_stat_cls, args, NULL);
  Py_DECREF(args);
  return stat;
}

/* strict jute bool: one byte, 0 or 1 only (jute.read_bool). Returns
 * -1 on error with c->err set. */
static int rd_bool(Cursor *c) {
  if (!need(c, 1)) return -1;
  uint8_t v = c->p[c->off];
  c->off += 1;
  if (v > 1) {
    snprintf(c->err, sizeof(c->err), "bad bool byte %d", v);
    return -1;
  }
  return v;
}

/* length-prefixed ACL list (records.read_acl): [ACL(Perm, Id)].
 * NULL on error (c->err or a pending exception). */
static PyObject *rd_acl_list(Cursor *c) {
  if (!need(c, 4)) return NULL;
  int32_t n = rd_i32(c);
  if (n < 0) n = 0;
  /* wire-controlled count: each ACL entry is >= 12 bytes (perms int +
   * two length prefixes); bound before allocating */
  if (!need(c, 12 * (Py_ssize_t)n)) return NULL;
  PyObject *lst = PyList_New(n);
  if (lst == NULL) return NULL;
  for (int32_t i = 0; i < n; ++i) {
    if (!need(c, 4)) {
      Py_DECREF(lst);
      return NULL;
    }
    int32_t perms = rd_i32(c);
    PyObject *scheme = rd_string(c);
    PyObject *ident = scheme ? rd_string(c) : NULL;
    PyObject *entry = NULL;
    if (ident != NULL) {
      PyObject *id_obj =
          PyObject_CallFunction(g_id_cls, "OO", scheme, ident);
      PyObject *perm_obj =
          id_obj ? PyObject_CallFunction(g_perm_cls, "i", perms) : NULL;
      if (perm_obj != NULL)
        entry = PyObject_CallFunction(g_acl_cls, "OO", perm_obj, id_obj);
      Py_XDECREF(perm_obj);
      Py_XDECREF(id_obj);
    }
    Py_XDECREF(scheme);
    Py_XDECREF(ident);
    if (entry == NULL) {
      Py_DECREF(lst);
      return NULL;
    }
    PyList_SET_ITEM(lst, i, entry);
  }
  return lst;
}

/* dict[int] lookup helper; returns borrowed ref or NULL (no exception) */
static PyObject *int_key_get(PyObject *dict, long long key) {
  PyObject *k = PyLong_FromLongLong(key);
  if (k == NULL) return NULL;
  PyObject *v = PyDict_GetItemWithError(dict, k); /* borrowed */
  Py_DECREF(k);
  if (v == NULL) PyErr_Clear();
  return v;
}

/* set pkt[key] = val, stealing val; -1 on failure (val still released) */
static int set_steal(PyObject *pkt, PyObject *key, PyObject *val) {
  if (val == NULL) return -1;
  int r = PyDict_SetItem(pkt, key, val);
  Py_DECREF(val);
  return r;
}

/* ---- one reply body ---- */

static int decode_body(Cursor *c, PyObject *pkt, int layout) {
  switch (layout) {
    case LAYOUT_EMPTY:
      return 0;
    case LAYOUT_CREATE:
      return set_steal(pkt, s_path, rd_string(c));
    case LAYOUT_STAT_ONLY:
      return set_steal(pkt, s_stat, rd_stat(c));
    case LAYOUT_GET_DATA: {
      if (set_steal(pkt, s_data, rd_bytes(c)) < 0) return -1;
      return set_steal(pkt, s_stat, rd_stat(c));
    }
    case LAYOUT_GET_CHILDREN:
    case LAYOUT_GET_CHILDREN2: {
      if (!need(c, 4)) return -1;
      int32_t n = rd_i32(c);
      if (n < 0) n = 0;
      /* the count is wire-controlled: every element needs >= 4 bytes
       * (its length prefix), so bound it by the remaining body before
       * allocating — a corrupt frame must fail as BAD_DECODE, not as a
       * multi-GB PyList_New */
      if (!need(c, 4 * (Py_ssize_t)n)) return -1;
      PyObject *lst = PyList_New(n);
      if (lst == NULL) return -1;
      for (int32_t i = 0; i < n; ++i) {
        PyObject *s = rd_string(c);
        if (s == NULL) {
          Py_DECREF(lst);
          return -1;
        }
        PyList_SET_ITEM(lst, i, s);
      }
      if (set_steal(pkt, s_children, lst) < 0) return -1;
      if (layout == LAYOUT_GET_CHILDREN2)
        return set_steal(pkt, s_stat, rd_stat(c));
      return 0;
    }
    case LAYOUT_GET_ACL: {
      if (set_steal(pkt, s_acl, rd_acl_list(c)) < 0) return -1;
      return set_steal(pkt, s_stat, rd_stat(c));
    }
    case LAYOUT_NOTIFICATION: {
      if (!need(c, 8)) return -1;
      int32_t type = rd_i32(c);
      int32_t state = rd_i32(c);
      PyObject *tname = int_key_get(g_notif_types, type);
      if (tname == NULL) {
        snprintf(c->err, sizeof(c->err), "%d is not a valid notification "
                 "type", type);
        return -1;
      }
      PyObject *sname = int_key_get(g_states, state);
      if (sname == NULL) {
        snprintf(c->err, sizeof(c->err), "%d is not a valid keeper state",
                 state);
        return -1;
      }
      if (PyDict_SetItem(pkt, s_type, tname) < 0) return -1;
      if (PyDict_SetItem(pkt, s_state, sname) < 0) return -1;
      return set_steal(pkt, s_path, rd_string(c));
    }
    default:
      snprintf(c->err, sizeof(c->err), "unknown layout %d", layout);
      return -1;
  }
}

/* ---- one frame -> packet dict (NULL + c->err / exception on error) -- */

static PyObject *decode_reply(Cursor *c, PyObject *xid_map) {
  if (!need(c, 16)) return NULL;
  int32_t xid = rd_i32(c);
  int64_t zxid = rd_i64(c);
  int32_t errc = rd_i32(c);

  PyObject *pkt = PyDict_New();
  if (pkt == NULL) return NULL;

  PyObject *opcode = NULL; /* borrowed or owned; track via owned flag */
  int opcode_owned = 0;
  switch (xid) { /* SPECIAL_XIDS (lib/zk-consts.js:135-138) */
    case -1: opcode = s_notification; break;
    case -2: opcode = s_ping; break;
    case -4: opcode = s_auth; break;
    case -8: opcode = s_set_watches; break;
    default: {
      PyObject *k = PyLong_FromLong(xid);
      if (k == NULL) goto fail;
      /* one reply per xid: pop, matching records.read_response
       * (get+del; PyDict_Pop is not public until 3.13) */
      opcode = PyDict_GetItemWithError(xid_map, k); /* borrowed */
      if (opcode == NULL) {
        Py_DECREF(k);
        if (PyErr_Occurred()) goto fail;
        snprintf(c->err, sizeof(c->err),
                 "reply xid %d matches no request", xid);
        goto fail;
      }
      Py_INCREF(opcode);
      opcode_owned = 1;
      if (PyDict_DelItem(xid_map, k) < 0) {
        Py_DECREF(k);
        goto fail;
      }
      Py_DECREF(k);
    }
  }

  if (set_steal(pkt, s_xid, PyLong_FromLong(xid)) < 0) goto fail;
  if (set_steal(pkt, s_zxid, PyLong_FromLongLong(zxid)) < 0) goto fail;
  PyObject *err_name = errc == 0 ? s_ok : int_key_get(g_err_names, errc);
  if (err_name != NULL) {
    if (PyDict_SetItem(pkt, s_err, err_name) < 0) goto fail;
  } else { /* unknown code -> 'ERROR_%d' (consts.err_name) */
    if (set_steal(pkt, s_err, PyUnicode_FromFormat("ERROR_%d", errc)) < 0)
      goto fail;
  }
  if (PyDict_SetItem(pkt, s_opcode, opcode) < 0) goto fail;

  if (errc == 0) {
    PyObject *layout = PyDict_GetItemWithError(g_layouts, opcode);
    if (layout == NULL) {
      if (PyErr_Occurred()) goto fail;
      snprintf(c->err, sizeof(c->err), "unsupported reply opcode");
      goto fail;
    }
    if (decode_body(c, pkt, (int)PyLong_AsLong(layout)) < 0) goto fail;
  }
  if (opcode_owned) Py_DECREF(opcode);
  return pkt;

fail:
  if (opcode_owned) Py_XDECREF(opcode);
  Py_DECREF(pkt);
  return NULL;
}

/* ---- one frame -> request dict (server direction) ---- */

static int decode_req_body(Cursor *c, PyObject *pkt, int layout) {
  switch (layout) {
    case RQ_EMPTY:
      return 0;
    case RQ_PATH:
      return set_steal(pkt, s_path, rd_string(c));
    case RQ_PATH_WATCH: {
      if (set_steal(pkt, s_path, rd_string(c)) < 0) return -1;
      int w = rd_bool(c);
      if (w < 0) return -1;
      return PyDict_SetItem(pkt, s_watch, w ? Py_True : Py_False);
    }
    case RQ_CREATE: {
      if (set_steal(pkt, s_path, rd_string(c)) < 0) return -1;
      if (set_steal(pkt, s_data, rd_bytes(c)) < 0) return -1;
      if (set_steal(pkt, s_acl, rd_acl_list(c)) < 0) return -1;
      if (!need(c, 4)) return -1;
      return set_steal(pkt, s_flags,
                       PyObject_CallFunction(g_create_flag_cls, "i",
                                             rd_i32(c)));
    }
    case RQ_DELETE: {
      if (set_steal(pkt, s_path, rd_string(c)) < 0) return -1;
      if (!need(c, 4)) return -1;
      return set_steal(pkt, s_version, PyLong_FromLong(rd_i32(c)));
    }
    case RQ_SET_DATA: {
      if (set_steal(pkt, s_path, rd_string(c)) < 0) return -1;
      if (set_steal(pkt, s_data, rd_bytes(c)) < 0) return -1;
      if (!need(c, 4)) return -1;
      return set_steal(pkt, s_version, PyLong_FromLong(rd_i32(c)));
    }
    case RQ_SET_WATCHES: {
      if (!need(c, 8)) return -1;
      PyObject *rel = PyLong_FromLongLong(rd_i64(c));
      if (set_steal(pkt, s_relZxid, rel) < 0) return -1;
      PyObject *events = PyDict_New();
      if (events == NULL) return -1;
      PyObject *kinds[3] = {s_dataChanged, s_createdOrDestroyed,
                            s_childrenChanged};
      for (int k = 0; k < 3; ++k) {
        if (!need(c, 4)) {
          Py_DECREF(events);
          return -1;
        }
        int32_t n = rd_i32(c);
        if (n < 0) n = 0;
        if (!need(c, 4 * (Py_ssize_t)n)) { /* wire-controlled count */
          Py_DECREF(events);
          return -1;
        }
        PyObject *lst = PyList_New(n);
        if (lst == NULL) {
          Py_DECREF(events);
          return -1;
        }
        for (int32_t i = 0; i < n; ++i) {
          PyObject *s = rd_string(c);
          if (s == NULL) {
            Py_DECREF(lst);
            Py_DECREF(events);
            return -1;
          }
          PyList_SET_ITEM(lst, i, s);
        }
        if (PyDict_SetItem(events, kinds[k], lst) < 0) {
          Py_DECREF(lst);
          Py_DECREF(events);
          return -1;
        }
        Py_DECREF(lst);
      }
      return set_steal(pkt, s_events, events);
    }
    default:
      snprintf(c->err, sizeof(c->err), "unknown request layout %d",
               layout);
      return -1;
  }
}

static PyObject *decode_request(Cursor *c) {
  if (!need(c, 8)) return NULL;
  int32_t xid = rd_i32(c);
  int32_t op = rd_i32(c);

  PyObject *entry = int_key_get(g_req_opcodes, op);
  if (entry == NULL) {
    /* match the Python spec's two distinct failures: a protocol-valid
     * opcode with no request reader vs a number outside the enum */
    PyObject *known = int_key_get(g_op_names, op);
    if (known != NULL)
      snprintf(c->err, sizeof(c->err), "unsupported opcode '%s'",
               PyUnicode_AsUTF8(known));
    else
      snprintf(c->err, sizeof(c->err), "%d is not a valid OpCode", op);
    return NULL;
  }
  PyObject *name = PyTuple_GET_ITEM(entry, 0);   /* borrowed */
  int layout = (int)PyLong_AsLong(PyTuple_GET_ITEM(entry, 1));

  PyObject *pkt = PyDict_New();
  if (pkt == NULL) return NULL;
  if (set_steal(pkt, s_xid, PyLong_FromLong(xid)) < 0) goto fail;
  if (PyDict_SetItem(pkt, s_opcode, name) < 0) goto fail;
  if (decode_req_body(c, pkt, layout) < 0) goto fail;
  return pkt;

fail:
  Py_DECREF(pkt);
  return NULL;
}

/* ---- module functions ---- */

static PyObject *py_setup(PyObject *self, PyObject *args) {
  PyObject *stat_cls, *acl_cls, *id_cls, *perm_cls, *create_flag_cls,
      *err_names, *notif_types, *states, *layouts, *req_opcodes,
      *op_names;
  if (!PyArg_ParseTuple(args, "OOOOOOOOOOO", &stat_cls, &acl_cls,
                        &id_cls, &perm_cls, &create_flag_cls,
                        &err_names, &notif_types, &states, &layouts,
                        &req_opcodes, &op_names))
    return NULL;
  /* rd_stat builds instances through tuple's tp_new */
  if (!PyType_Check(stat_cls) ||
      !PyType_IsSubtype((PyTypeObject *)stat_cls, &PyTuple_Type)) {
    PyErr_SetString(PyExc_TypeError, "Stat must be a tuple subclass");
    return NULL;
  }
  Py_INCREF(stat_cls); Py_XSETREF(g_stat_cls, stat_cls);
  Py_INCREF(acl_cls); Py_XSETREF(g_acl_cls, acl_cls);
  Py_INCREF(id_cls); Py_XSETREF(g_id_cls, id_cls);
  Py_INCREF(perm_cls); Py_XSETREF(g_perm_cls, perm_cls);
  Py_INCREF(create_flag_cls);
  Py_XSETREF(g_create_flag_cls, create_flag_cls);
  Py_INCREF(err_names); Py_XSETREF(g_err_names, err_names);
  Py_INCREF(notif_types); Py_XSETREF(g_notif_types, notif_types);
  Py_INCREF(states); Py_XSETREF(g_states, states);
  Py_INCREF(layouts); Py_XSETREF(g_layouts, layouts);
  Py_INCREF(req_opcodes); Py_XSETREF(g_req_opcodes, req_opcodes);
  Py_INCREF(op_names); Py_XSETREF(g_op_names, op_names);
  Py_RETURN_NONE;
}

/* shared frame walk: slice complete frames, decode each body via the
 * reply (xid_map != NULL) or request decoder, with the PacketCodec
 * error contract.  Consumes/releases `view`. */
static PyObject *decode_stream(Py_buffer view, PyObject *xid_map,
                               int max_packet) {
  const char *what = xid_map != NULL ? "Response" : "Request";
  if (g_stat_cls == NULL) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_RuntimeError, "setup() not called");
    return NULL;
  }

  const uint8_t *buf = (const uint8_t *)view.buf;
  Py_ssize_t len = view.len;

  PyObject *pkts = PyList_New(0);
  if (pkts == NULL) {
    PyBuffer_Release(&view);
    return NULL;
  }

  const char *err_kind = NULL;
  char err_msg[256] = {0};
  Py_ssize_t consumed = 0;

  /* pass 1: frame boundaries (so a bad prefix drops earlier frames
   * exactly like FrameDecoder.feed raising mid-scan) */
  Py_ssize_t off = 0, end_of_frames = 0;
  while (len - off >= 4) {
    int32_t ln = (int32_t)(((uint32_t)buf[off] << 24) |
                           ((uint32_t)buf[off + 1] << 16) |
                           ((uint32_t)buf[off + 2] << 8) |
                           (uint32_t)buf[off + 3]);
    if (ln < 0 || ln > max_packet) {
      err_kind = "BAD_LENGTH";
      snprintf(err_msg, sizeof(err_msg), "Invalid ZK packet length %d",
               ln);
      consumed = off;
      goto done;
    }
    if (len - off < 4 + (Py_ssize_t)ln) break;
    off += 4 + ln;
    end_of_frames = off;
  }
  consumed = end_of_frames;

  /* pass 2: decode each frame body */
  off = 0;
  while (off < end_of_frames) {
    int32_t ln = (int32_t)(((uint32_t)buf[off] << 24) |
                           ((uint32_t)buf[off + 1] << 16) |
                           ((uint32_t)buf[off + 2] << 8) |
                           (uint32_t)buf[off + 3]);
    Cursor c = {buf + off + 4, ln, 0, {0}};
    PyObject *pkt = xid_map != NULL ? decode_reply(&c, xid_map)
                                    : decode_request(&c);
    if (pkt == NULL) {
      if (PyErr_Occurred()) { /* real exception (OOM etc.) */
        Py_DECREF(pkts);
        PyBuffer_Release(&view);
        return NULL;
      }
      err_kind = "BAD_DECODE";
      snprintf(err_msg, sizeof(err_msg), "Failed to decode %s: %s",
               what, c.err);
      goto done;
    }
    if (PyList_Append(pkts, pkt) < 0) {
      Py_DECREF(pkt);
      Py_DECREF(pkts);
      PyBuffer_Release(&view);
      return NULL;
    }
    Py_DECREF(pkt);
    off += 4 + ln;
  }

done:
  PyBuffer_Release(&view);
  PyObject *ret =
      err_kind == NULL
          ? Py_BuildValue("(OnOO)", pkts, consumed, Py_None, Py_None)
          : Py_BuildValue("(Onss)", pkts, consumed, err_kind, err_msg);
  Py_DECREF(pkts); /* BuildValue's "O" took its own reference */
  return ret;
}

static PyObject *py_decode_responses(PyObject *self, PyObject *args) {
  Py_buffer view;
  PyObject *xid_map;
  int max_packet;
  if (!PyArg_ParseTuple(args, "y*O!i", &view, &PyDict_Type, &xid_map,
                        &max_packet))
    return NULL;
  return decode_stream(view, xid_map, max_packet);
}

static PyObject *py_decode_requests(PyObject *self, PyObject *args) {
  Py_buffer view;
  int max_packet;
  if (!PyArg_ParseTuple(args, "y*i", &view, &max_packet)) return NULL;
  return decode_stream(view, NULL, max_packet);
}

static PyObject *py_abi_version(PyObject *self, PyObject *noargs) {
  return PyLong_FromLong(3);
}

static PyMethodDef methods[] = {
    {"setup", py_setup, METH_VARARGS,
     "setup(Stat, ACL, Id, Perm, CreateFlag, err_names, notif_types, "
     "states, layouts, req_opcodes, op_names)"},
    {"decode_responses", py_decode_responses, METH_VARARGS,
     "decode_responses(buf, xid_map, max_packet) -> "
     "(pkts, consumed, err_kind, err_msg)"},
    {"decode_requests", py_decode_requests, METH_VARARGS,
     "decode_requests(buf, max_packet) -> "
     "(pkts, consumed, err_kind, err_msg)"},
    {"abi_version", py_abi_version, METH_NOARGS, "native ABI version"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_zkwire_ext",
    "C decoder for the zkstream_tpu receive hot path", -1, methods};

PyMODINIT_FUNC PyInit__zkwire_ext(void) {
  s_xid = PyUnicode_InternFromString("xid");
  s_zxid = PyUnicode_InternFromString("zxid");
  s_err = PyUnicode_InternFromString("err");
  s_opcode = PyUnicode_InternFromString("opcode");
  s_data = PyUnicode_InternFromString("data");
  s_stat = PyUnicode_InternFromString("stat");
  s_path = PyUnicode_InternFromString("path");
  s_children = PyUnicode_InternFromString("children");
  s_acl = PyUnicode_InternFromString("acl");
  s_type = PyUnicode_InternFromString("type");
  s_state = PyUnicode_InternFromString("state");
  s_watch = PyUnicode_InternFromString("watch");
  s_version = PyUnicode_InternFromString("version");
  s_relZxid = PyUnicode_InternFromString("relZxid");
  s_events = PyUnicode_InternFromString("events");
  s_flags = PyUnicode_InternFromString("flags");
  s_notification = PyUnicode_InternFromString("NOTIFICATION");
  s_ping = PyUnicode_InternFromString("PING");
  s_auth = PyUnicode_InternFromString("AUTH");
  s_set_watches = PyUnicode_InternFromString("SET_WATCHES");
  s_ok = PyUnicode_InternFromString("OK");
  s_dataChanged = PyUnicode_InternFromString("dataChanged");
  s_createdOrDestroyed =
      PyUnicode_InternFromString("createdOrDestroyed");
  s_childrenChanged = PyUnicode_InternFromString("childrenChanged");
  return PyModule_Create(&moduledef);
}
