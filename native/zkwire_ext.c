/* zkwire_ext: CPython-extension decoder for the per-connection receive
 * hot path.
 *
 * Why this exists (see tools/profile_hotpath.py for the numbers): the
 * pure-Python scalar decode of a GET_DATA reply stream runs at ~15-25
 * MiB/s, and >90% of that time is jute primitive reads — per-field
 * struct.unpack_from calls, bounds checks, and dict/dataclass plumbing
 * in zkstream_tpu/protocol/{jute,records}.py.  Framing alone is cheap
 * (the plain-C-ABI scanner in zkwire.cpp covers it), so the profitable
 * native boundary is the *whole* receive transform: accumulated bytes
 * -> list of packet dicts, in one C pass.  That is the same span the
 * reference executes per socket read (frame loop lib/zk-streams.js:
 * 39-99 + reply parse lib/zk-buffer.js:275-370), and the host-side
 * counterpart of the batched TPU pipeline (ops/pipeline.py).
 *
 * Contract (mirrors PacketCodec.decode exactly; A/B-tested in
 * tests/test_native_ext.py):
 *
 *   decode_responses(buf, xid_map, max_packet)
 *     -> (pkts, consumed, err_kind, err_msg)
 *
 * - Slices every complete length-prefixed frame out of buf[0:len];
 *   `consumed` is the byte offset the caller must drop from its
 *   accumulation buffer.
 * - Each frame decodes to the same packet dict the Python codec builds:
 *   xid/zxid/err + opcode-specific body fields (data/stat/path/children/
 *   acl/type/state), with Stat/ACL/Id constructed through the Python
 *   classes registered via setup().
 * - Bad length prefix (negative or > max_packet): err_kind BAD_LENGTH,
 *   consumed = offset of the offending prefix, pkts = [] (frames
 *   before it are consumed-and-dropped — identical to
 *   FrameDecoder.feed raising mid-scan).
 * - Undecodable frame body: err_kind BAD_DECODE, pkts = packets decoded
 *   before the bad frame (PacketCodec attaches them to the raised
 *   error), consumed = all complete frames (they left the buffer in the
 *   scalar path too).
 * - xids are popped from xid_map exactly as records.read_response does.
 *
 * Built with a bare `gcc -shared -fPIC` against the interpreter's
 * headers; loaded via utils/native.py with the same
 * version-named-artifact discipline as the C-ABI scanner.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <errno.h>
#include <stdint.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#ifdef __linux__
#include <sys/mman.h>
#include <sys/syscall.h>
#endif

/* ---- registered Python objects (held forever once set) ---- */

static PyObject *g_stat_cls;    /* records.Stat */
static PyObject *g_acl_cls;    /* records.ACL */
static PyObject *g_id_cls;     /* records.Id */
static PyObject *g_perm_cls;   /* consts.Perm (IntFlag) */
static PyObject *g_create_flag_cls; /* consts.CreateFlag (IntFlag) */
static PyObject *g_err_names;  /* dict int -> str (ErrCode names) */
static PyObject *g_notif_types; /* dict int -> str */
static PyObject *g_states;     /* dict int -> str (KeeperState names) */
static PyObject *g_layouts;    /* dict opcode-str -> layout int */
static PyObject *g_req_opcodes; /* dict int -> (name, req-layout int) */
static PyObject *g_op_names;   /* dict int -> str: EVERY valid OpCode */

/* interned key + special-opcode strings */
static PyObject *s_xid, *s_zxid, *s_err, *s_opcode, *s_data, *s_stat,
    *s_path, *s_children, *s_acl, *s_type, *s_state, *s_watch,
    *s_version, *s_relZxid, *s_events, *s_flags, *s_mode;
static PyObject *s_notification, *s_ping, *s_auth, *s_set_watches, *s_ok;
static PyObject *s_dataChanged, *s_createdOrDestroyed,
    *s_childrenChanged, *s_persistent, *s_persistentRecursive;
/* MULTI (opcode 14) framing: result/ops keys + sub-op names */
static PyObject *s_results, *s_op, *s_ops, *s_op_create, *s_op_delete,
    *s_op_set_data, *s_op_check, *s_op_error;
/* attribute names for ACL entries (records.ACL / records.Id) */
static PyObject *s_perms, *s_scheme, *s_id_attr;

/* layout enum — the Python side builds g_layouts with these values */
enum {
  LAYOUT_EMPTY = 0,
  LAYOUT_GET_CHILDREN = 1,
  LAYOUT_GET_CHILDREN2 = 2,
  LAYOUT_CREATE = 3,
  LAYOUT_GET_ACL = 4,
  LAYOUT_GET_DATA = 5,
  LAYOUT_STAT_ONLY = 6,
  LAYOUT_NOTIFICATION = 7,
  LAYOUT_MULTI = 8,
};

/* request-body layouts (server direction) — g_req_opcodes values */
enum {
  RQ_EMPTY = 0,
  RQ_PATH = 1,
  RQ_PATH_WATCH = 2,
  RQ_CREATE = 3,
  RQ_DELETE = 4,
  RQ_SET_DATA = 5,
  RQ_SET_WATCHES = 6,
  RQ_MULTI = 7,
  RQ_ADD_WATCH = 8,
  RQ_SET_WATCHES2 = 9,
};

/* ---- byte readers (big-endian, bounds-checked) ---- */

typedef struct {
  const uint8_t *p;
  Py_ssize_t len;
  Py_ssize_t off;
  char err[192]; /* non-empty => decode error */
  int unsupported; /* protocol-valid opcode this tier has no layout
                    * for (none today — MULTI landed in abi 9): the
                    * frame is left in the buffer and the Python
                    * spec tier decodes it */
} Cursor;

static int need(Cursor *c, Py_ssize_t n) {
  if (c->off + n > c->len) {
    snprintf(c->err, sizeof(c->err),
             "need %zd bytes at offset %zd, have %zd", n, c->off,
             c->len - c->off);
    return 0;
  }
  return 1;
}

static int32_t rd_i32(Cursor *c) {
  const uint8_t *p = c->p + c->off;
  c->off += 4;
  return (int32_t)(((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
                   ((uint32_t)p[2] << 8) | (uint32_t)p[3]);
}

static int64_t rd_i64(Cursor *c) {
  const uint8_t *p = c->p + c->off;
  c->off += 8;
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | p[i];
  return (int64_t)v;
}

/* int-length-prefixed UTF-8 string; negative length => "" (the jute
 * empty-buffer quirk, lib/jute-buffer.js:99-100). NULL on error. */
static PyObject *rd_string(Cursor *c) {
  if (!need(c, 4)) return NULL;
  int32_t ln = rd_i32(c);
  if (ln < 0) return PyUnicode_FromStringAndSize("", 0);
  if (!need(c, ln)) return NULL;
  PyObject *s =
      PyUnicode_DecodeUTF8((const char *)c->p + c->off, ln, NULL);
  if (s == NULL) {
    /* surface as a decode error, not a raised exception */
    PyErr_Clear();
    snprintf(c->err, sizeof(c->err), "invalid utf-8 string at offset %zd",
             c->off);
    return NULL;
  }
  c->off += ln;
  return s;
}

static PyObject *rd_bytes(Cursor *c) {
  if (!need(c, 4)) return NULL;
  int32_t ln = rd_i32(c);
  if (ln < 0) return PyBytes_FromStringAndSize("", 0);
  if (!need(c, ln)) return NULL;
  PyObject *b =
      PyBytes_FromStringAndSize((const char *)c->p + c->off, ln);
  c->off += ln;
  return b;
}

/* the 68-byte Stat record in one bounds check
 * (reference: lib/zk-buffer.js:428-442).
 *
 * Stat is a NamedTuple, i.e. a tuple subclass, so the instance is
 * built through tuple's own tp_new — the exact effect of
 * `tuple.__new__(Stat, fields)` — skipping the generated Python-level
 * __new__ (which costs ~10x the tuple itself on the hot path). */
static PyObject *rd_stat(Cursor *c) {
  if (!need(c, 68)) return NULL;
  PyObject *vals = PyTuple_New(11);
  if (vals == NULL) return NULL;
#define STAT_FIELD(i, expr)                 \
  do {                                      \
    PyObject *v_ = (expr);                  \
    if (v_ == NULL) {                       \
      Py_DECREF(vals);                      \
      return NULL;                          \
    }                                       \
    PyTuple_SET_ITEM(vals, (i), v_);        \
  } while (0)
  STAT_FIELD(0, PyLong_FromLongLong(rd_i64(c)));  /* czxid */
  STAT_FIELD(1, PyLong_FromLongLong(rd_i64(c)));  /* mzxid */
  STAT_FIELD(2, PyLong_FromLongLong(rd_i64(c)));  /* ctime */
  STAT_FIELD(3, PyLong_FromLongLong(rd_i64(c)));  /* mtime */
  STAT_FIELD(4, PyLong_FromLong(rd_i32(c)));      /* version */
  STAT_FIELD(5, PyLong_FromLong(rd_i32(c)));      /* cversion */
  STAT_FIELD(6, PyLong_FromLong(rd_i32(c)));      /* aversion */
  STAT_FIELD(7, PyLong_FromLongLong(rd_i64(c)));  /* ephemeralOwner */
  STAT_FIELD(8, PyLong_FromLong(rd_i32(c)));      /* dataLength */
  STAT_FIELD(9, PyLong_FromLong(rd_i32(c)));      /* numChildren */
  STAT_FIELD(10, PyLong_FromLongLong(rd_i64(c))); /* pzxid */
#undef STAT_FIELD
  PyObject *args = PyTuple_Pack(1, vals);
  Py_DECREF(vals);
  if (args == NULL) return NULL;
  PyObject *stat =
      PyTuple_Type.tp_new((PyTypeObject *)g_stat_cls, args, NULL);
  Py_DECREF(args);
  return stat;
}

/* strict jute bool: one byte, 0 or 1 only (jute.read_bool). Returns
 * -1 on error with c->err set. */
static int rd_bool(Cursor *c) {
  if (!need(c, 1)) return -1;
  uint8_t v = c->p[c->off];
  c->off += 1;
  if (v > 1) {
    snprintf(c->err, sizeof(c->err), "bad bool byte %d", v);
    return -1;
  }
  return v;
}

/* length-prefixed ACL list (records.read_acl): [ACL(Perm, Id)].
 * NULL on error (c->err or a pending exception). */
static PyObject *rd_acl_list(Cursor *c) {
  if (!need(c, 4)) return NULL;
  int32_t n = rd_i32(c);
  if (n < 0) n = 0;
  /* wire-controlled count: each ACL entry is >= 12 bytes (perms int +
   * two length prefixes); bound before allocating */
  if (!need(c, 12 * (Py_ssize_t)n)) return NULL;
  PyObject *lst = PyList_New(n);
  if (lst == NULL) return NULL;
  for (int32_t i = 0; i < n; ++i) {
    if (!need(c, 4)) {
      Py_DECREF(lst);
      return NULL;
    }
    int32_t perms = rd_i32(c);
    PyObject *scheme = rd_string(c);
    PyObject *ident = scheme ? rd_string(c) : NULL;
    PyObject *entry = NULL;
    if (ident != NULL) {
      PyObject *id_obj =
          PyObject_CallFunction(g_id_cls, "OO", scheme, ident);
      PyObject *perm_obj =
          id_obj ? PyObject_CallFunction(g_perm_cls, "i", perms) : NULL;
      if (perm_obj != NULL)
        entry = PyObject_CallFunction(g_acl_cls, "OO", perm_obj, id_obj);
      Py_XDECREF(perm_obj);
      Py_XDECREF(id_obj);
    }
    Py_XDECREF(scheme);
    Py_XDECREF(ident);
    if (entry == NULL) {
      Py_DECREF(lst);
      return NULL;
    }
    PyList_SET_ITEM(lst, i, entry);
  }
  return lst;
}

/* dict[int] lookup helper; returns borrowed ref or NULL (no exception).
 * NULL uniformly means "treat as absent": callers take their scalar
 * fallback branch, so a failure here (key alloc under OOM included)
 * must clear the error — returning NULL with a live exception would
 * let a success value escape with the exception still set. */
static PyObject *int_key_get(PyObject *dict, long long key) {
  PyObject *k = PyLong_FromLongLong(key);
  if (k == NULL) {
    PyErr_Clear();
    return NULL;
  }
  PyObject *v = PyDict_GetItemWithError(dict, k); /* borrowed */
  Py_DECREF(k);
  if (v == NULL) PyErr_Clear();
  return v;
}

/* set pkt[key] = val, stealing val; -1 on failure (val still released) */
static int set_steal(PyObject *pkt, PyObject *key, PyObject *val) {
  if (val == NULL) return -1;
  int r = PyDict_SetItem(pkt, key, val);
  Py_DECREF(val);
  return r;
}

/* ---- one reply body ---- */

static int decode_body(Cursor *c, PyObject *pkt, int layout) {
  switch (layout) {
    case LAYOUT_EMPTY:
      return 0;
    case LAYOUT_CREATE:
      return set_steal(pkt, s_path, rd_string(c));
    case LAYOUT_STAT_ONLY:
      return set_steal(pkt, s_stat, rd_stat(c));
    case LAYOUT_GET_DATA: {
      if (set_steal(pkt, s_data, rd_bytes(c)) < 0) return -1;
      return set_steal(pkt, s_stat, rd_stat(c));
    }
    case LAYOUT_GET_CHILDREN:
    case LAYOUT_GET_CHILDREN2: {
      if (!need(c, 4)) return -1;
      int32_t n = rd_i32(c);
      if (n < 0) n = 0;
      /* the count is wire-controlled: every element needs >= 4 bytes
       * (its length prefix), so bound it by the remaining body before
       * allocating — a corrupt frame must fail as BAD_DECODE, not as a
       * multi-GB PyList_New */
      if (!need(c, 4 * (Py_ssize_t)n)) return -1;
      PyObject *lst = PyList_New(n);
      if (lst == NULL) return -1;
      for (int32_t i = 0; i < n; ++i) {
        PyObject *s = rd_string(c);
        if (s == NULL) {
          Py_DECREF(lst);
          return -1;
        }
        PyList_SET_ITEM(lst, i, s);
      }
      if (set_steal(pkt, s_children, lst) < 0) return -1;
      if (layout == LAYOUT_GET_CHILDREN2)
        return set_steal(pkt, s_stat, rd_stat(c));
      return 0;
    }
    case LAYOUT_GET_ACL: {
      if (set_steal(pkt, s_acl, rd_acl_list(c)) < 0) return -1;
      return set_steal(pkt, s_stat, rd_stat(c));
    }
    case LAYOUT_NOTIFICATION: {
      if (!need(c, 8)) return -1;
      int32_t type = rd_i32(c);
      int32_t state = rd_i32(c);
      PyObject *tname = int_key_get(g_notif_types, type);
      if (tname == NULL) {
        snprintf(c->err, sizeof(c->err), "%d is not a valid notification "
                 "type", type);
        return -1;
      }
      PyObject *sname = int_key_get(g_states, state);
      if (sname == NULL) {
        snprintf(c->err, sizeof(c->err), "%d is not a valid keeper state",
                 state);
        return -1;
      }
      if (PyDict_SetItem(pkt, s_type, tname) < 0) return -1;
      if (PyDict_SetItem(pkt, s_state, sname) < 0) return -1;
      return set_steal(pkt, s_path, rd_string(c));
    }
    case LAYOUT_MULTI: {
      /* jute MultiResponse (opcode 14): `int type | bool done | int
       * err` headers, OK results carrying the single-op reply body
       * (create: path; setData: Stat; delete/check: header only),
       * type -1 an ErrorResult whose body repeats the code,
       * terminated by a done header.  Mirrors
       * records._read_multi_resp exactly (which, like the upstream
       * client, does not re-check the terminator's type). */
      PyObject *results = PyList_New(0);
      if (results == NULL) return -1;
      for (;;) {
        if (!need(c, 9)) goto multi_fail;
        int32_t mtype = rd_i32(c);
        int done = rd_bool(c);
        if (done < 0) goto multi_fail;
        int32_t errv = rd_i32(c);
        if (done) break;
        PyObject *res = PyDict_New();
        if (res == NULL) goto multi_fail;
        int bad = 0;
        if (mtype == -1) {
          if (!need(c, 4)) {
            Py_DECREF(res);
            goto multi_fail;
          }
          (void)rd_i32(c);    /* ErrorResult body repeats the code */
          bad |= PyDict_SetItem(res, s_op, s_op_error) < 0;
          PyObject *en = int_key_get(g_err_names, errv);
          if (en != NULL) {   /* borrowed */
            bad |= PyDict_SetItem(res, s_err, en) < 0;
          } else {            /* consts.err_name's ERROR_%d shape */
            bad |= set_steal(res, s_err,
                             PyUnicode_FromFormat("ERROR_%d",
                                                  errv)) < 0;
          }
        } else if (mtype == 1) {           /* OpCode.CREATE */
          bad |= PyDict_SetItem(res, s_op, s_op_create) < 0;
          bad |= set_steal(res, s_path, rd_string(c)) < 0;
        } else if (mtype == 5) {           /* OpCode.SET_DATA */
          bad |= PyDict_SetItem(res, s_op, s_op_set_data) < 0;
          bad |= set_steal(res, s_stat, rd_stat(c)) < 0;
        } else if (mtype == 2) {           /* OpCode.DELETE */
          bad |= PyDict_SetItem(res, s_op, s_op_delete) < 0;
        } else if (mtype == 13) {          /* OpCode.CHECK */
          bad |= PyDict_SetItem(res, s_op, s_op_check) < 0;
        } else {
          snprintf(c->err, sizeof(c->err),
                   "unsupported multi result type %d", mtype);
          bad = 1;
        }
        if (bad || PyList_Append(results, res) < 0) {
          Py_DECREF(res);
          goto multi_fail;
        }
        Py_DECREF(res);
      }
      return set_steal(pkt, s_results, results);
    multi_fail:
      Py_DECREF(results);
      return -1;
    }
    default:
      snprintf(c->err, sizeof(c->err), "unknown layout %d", layout);
      return -1;
  }
}

/* ---- one frame -> packet dict (NULL + c->err / exception on error) -- */

static PyObject *decode_reply(Cursor *c, PyObject *xid_map) {
  if (!need(c, 16)) return NULL;
  int32_t xid = rd_i32(c);
  int64_t zxid = rd_i64(c);
  int32_t errc = rd_i32(c);

  PyObject *pkt = PyDict_New();
  if (pkt == NULL) return NULL;

  PyObject *opcode = NULL; /* borrowed or owned; track via owned flag */
  int opcode_owned = 0;
  switch (xid) { /* SPECIAL_XIDS (lib/zk-consts.js:135-138) */
    case -1: opcode = s_notification; break;
    case -2: opcode = s_ping; break;
    case -4: opcode = s_auth; break;
    case -8: opcode = s_set_watches; break;
    default: {
      PyObject *k = PyLong_FromLong(xid);
      if (k == NULL) goto fail;
      /* one reply per xid: pop, matching records.read_response
       * (get+del; PyDict_Pop is not public until 3.13) */
      opcode = PyDict_GetItemWithError(xid_map, k); /* borrowed */
      if (opcode == NULL) {
        Py_DECREF(k);
        if (PyErr_Occurred()) goto fail;
        snprintf(c->err, sizeof(c->err),
                 "reply xid %d matches no request", xid);
        goto fail;
      }
      Py_INCREF(opcode);
      opcode_owned = 1;
      /* punt BEFORE consuming the xid: a reply opcode this tier has
       * no body layout for (none registered today) goes back to the
       * Python spec, which pops the xid itself.  Error replies carry
       * no body, so they stay decodable here whatever the opcode. */
      if (errc == 0) {
        PyObject *layout = PyDict_GetItemWithError(g_layouts, opcode);
        if (layout == NULL) {
          Py_DECREF(k);
          if (PyErr_Occurred()) goto fail;
          snprintf(c->err, sizeof(c->err), "unsupported reply opcode");
          c->unsupported = 1;
          goto fail;
        }
      }
      if (PyDict_DelItem(xid_map, k) < 0) {
        Py_DECREF(k);
        goto fail;
      }
      Py_DECREF(k);
    }
  }

  if (set_steal(pkt, s_xid, PyLong_FromLong(xid)) < 0) goto fail;
  if (set_steal(pkt, s_zxid, PyLong_FromLongLong(zxid)) < 0) goto fail;
  PyObject *err_name = errc == 0 ? s_ok : int_key_get(g_err_names, errc);
  if (err_name != NULL) {
    if (PyDict_SetItem(pkt, s_err, err_name) < 0) goto fail;
  } else { /* unknown code -> 'ERROR_%d' (consts.err_name) */
    if (set_steal(pkt, s_err, PyUnicode_FromFormat("ERROR_%d", errc)) < 0)
      goto fail;
  }
  if (PyDict_SetItem(pkt, s_opcode, opcode) < 0) goto fail;

  if (errc == 0) {
    PyObject *layout = PyDict_GetItemWithError(g_layouts, opcode);
    if (layout == NULL) {
      if (PyErr_Occurred()) goto fail;
      snprintf(c->err, sizeof(c->err), "unsupported reply opcode");
      goto fail;
    }
    if (decode_body(c, pkt, (int)PyLong_AsLong(layout)) < 0) goto fail;
  }
  if (opcode_owned) Py_DECREF(opcode);
  return pkt;

fail:
  if (opcode_owned) Py_XDECREF(opcode);
  Py_DECREF(pkt);
  return NULL;
}

/* ---- one frame -> request dict (server direction) ---- */

static int decode_req_body(Cursor *c, PyObject *pkt, int layout) {
  switch (layout) {
    case RQ_EMPTY:
      return 0;
    case RQ_PATH:
      return set_steal(pkt, s_path, rd_string(c));
    case RQ_PATH_WATCH: {
      if (set_steal(pkt, s_path, rd_string(c)) < 0) return -1;
      int w = rd_bool(c);
      if (w < 0) return -1;
      return PyDict_SetItem(pkt, s_watch, w ? Py_True : Py_False);
    }
    case RQ_CREATE: {
      if (set_steal(pkt, s_path, rd_string(c)) < 0) return -1;
      if (set_steal(pkt, s_data, rd_bytes(c)) < 0) return -1;
      if (set_steal(pkt, s_acl, rd_acl_list(c)) < 0) return -1;
      if (!need(c, 4)) return -1;
      return set_steal(pkt, s_flags,
                       PyObject_CallFunction(g_create_flag_cls, "i",
                                             rd_i32(c)));
    }
    case RQ_DELETE: {
      if (set_steal(pkt, s_path, rd_string(c)) < 0) return -1;
      if (!need(c, 4)) return -1;
      return set_steal(pkt, s_version, PyLong_FromLong(rd_i32(c)));
    }
    case RQ_SET_DATA: {
      if (set_steal(pkt, s_path, rd_string(c)) < 0) return -1;
      if (set_steal(pkt, s_data, rd_bytes(c)) < 0) return -1;
      if (!need(c, 4)) return -1;
      return set_steal(pkt, s_version, PyLong_FromLong(rd_i32(c)));
    }
    case RQ_ADD_WATCH: {
      /* AddWatchRequest: path + AddWatchMode int (opcode 106) */
      if (set_steal(pkt, s_path, rd_string(c)) < 0) return -1;
      if (!need(c, 4)) return -1;
      return set_steal(pkt, s_mode, PyLong_FromLong(rd_i32(c)));
    }
    case RQ_SET_WATCHES:
    case RQ_SET_WATCHES2: {
      /* SET_WATCHES2 appends the two persistent lists after the
       * three legacy one-shot lists — same framing otherwise */
      int nkinds = layout == RQ_SET_WATCHES2 ? 5 : 3;
      if (!need(c, 8)) return -1;
      PyObject *rel = PyLong_FromLongLong(rd_i64(c));
      if (set_steal(pkt, s_relZxid, rel) < 0) return -1;
      PyObject *events = PyDict_New();
      if (events == NULL) return -1;
      PyObject *kinds[5] = {s_dataChanged, s_createdOrDestroyed,
                            s_childrenChanged, s_persistent,
                            s_persistentRecursive};
      for (int k = 0; k < nkinds; ++k) {
        if (!need(c, 4)) {
          Py_DECREF(events);
          return -1;
        }
        int32_t n = rd_i32(c);
        if (n < 0) n = 0;
        if (!need(c, 4 * (Py_ssize_t)n)) { /* wire-controlled count */
          Py_DECREF(events);
          return -1;
        }
        PyObject *lst = PyList_New(n);
        if (lst == NULL) {
          Py_DECREF(events);
          return -1;
        }
        for (int32_t i = 0; i < n; ++i) {
          PyObject *s = rd_string(c);
          if (s == NULL) {
            Py_DECREF(lst);
            Py_DECREF(events);
            return -1;
          }
          PyList_SET_ITEM(lst, i, s);
        }
        if (PyDict_SetItem(events, kinds[k], lst) < 0) {
          Py_DECREF(lst);
          Py_DECREF(events);
          return -1;
        }
        Py_DECREF(lst);
      }
      return set_steal(pkt, s_events, events);
    }
    case RQ_MULTI: {
      /* jute MultiTransactionRecord (opcode 14): headers as in the
       * response direction; sub-op bodies reuse the single-op
       * request layouts (create/delete/setData; check shares
       * delete's path+version shape), and the terminator's type
       * must be -1 — mirrors records._read_multi exactly. */
      PyObject *ops = PyList_New(0);
      if (ops == NULL) return -1;
      for (;;) {
        if (!need(c, 9)) goto rq_multi_fail;
        int32_t mtype = rd_i32(c);
        int done = rd_bool(c);
        if (done < 0) goto rq_multi_fail;
        (void)rd_i32(c);                  /* err: always -1 here */
        if (done) {
          if (mtype != -1) {
            snprintf(c->err, sizeof(c->err),
                     "multi terminator carries type %d", mtype);
            goto rq_multi_fail;
          }
          break;
        }
        PyObject *name;
        int sublayout;
        if (mtype == 1) {                  /* OpCode.CREATE */
          name = s_op_create;
          sublayout = RQ_CREATE;
        } else if (mtype == 2) {           /* OpCode.DELETE */
          name = s_op_delete;
          sublayout = RQ_DELETE;
        } else if (mtype == 5) {           /* OpCode.SET_DATA */
          name = s_op_set_data;
          sublayout = RQ_SET_DATA;
        } else if (mtype == 13) {          /* OpCode.CHECK */
          name = s_op_check;
          sublayout = RQ_DELETE;   /* same path+version body */
        } else {
          snprintf(c->err, sizeof(c->err),
                   "unsupported multi sub-op type %d", mtype);
          goto rq_multi_fail;
        }
        PyObject *sub = PyDict_New();
        if (sub == NULL) goto rq_multi_fail;
        if (PyDict_SetItem(sub, s_op, name) < 0 ||
            decode_req_body(c, sub, sublayout) < 0 ||
            PyList_Append(ops, sub) < 0) {
          Py_DECREF(sub);
          goto rq_multi_fail;
        }
        Py_DECREF(sub);
      }
      return set_steal(pkt, s_ops, ops);
    rq_multi_fail:
      Py_DECREF(ops);
      return -1;
    }
    default:
      snprintf(c->err, sizeof(c->err), "unknown request layout %d",
               layout);
      return -1;
  }
}

static PyObject *decode_request(Cursor *c) {
  if (!need(c, 8)) return NULL;
  int32_t xid = rd_i32(c);
  int32_t op = rd_i32(c);

  PyObject *entry = int_key_get(g_req_opcodes, op);
  if (entry == NULL) {
    /* match the Python spec's two distinct failures: a protocol-valid
     * opcode with no request reader vs a number outside the enum.  A
     * valid opcode is a PUNT, not an error: the spec tier may carry a
     * reader this tier does not — the driver leaves the frame in the
     * buffer and the Python path decides. */
    PyObject *known = int_key_get(g_op_names, op);
    if (known != NULL) {
      snprintf(c->err, sizeof(c->err), "unsupported opcode '%s'",
               PyUnicode_AsUTF8(known));
      c->unsupported = 1;
    } else {
      snprintf(c->err, sizeof(c->err), "%d is not a valid OpCode", op);
    }
    return NULL;
  }
  PyObject *name = PyTuple_GET_ITEM(entry, 0);   /* borrowed */
  int layout = (int)PyLong_AsLong(PyTuple_GET_ITEM(entry, 1));

  PyObject *pkt = PyDict_New();
  if (pkt == NULL) return NULL;
  if (set_steal(pkt, s_xid, PyLong_FromLong(xid)) < 0) goto fail;
  if (PyDict_SetItem(pkt, s_opcode, name) < 0) goto fail;
  if (decode_req_body(c, pkt, layout) < 0) goto fail;
  return pkt;

fail:
  Py_DECREF(pkt);
  return NULL;
}

/* ---- encode (steady state, both directions) ----------------------
 *
 * Best-effort accelerator with the Python JuteWriter as the semantic
 * spec and fallback: any unexpected shape/type/range returns NULL
 * WITHOUT setting an exception, and PacketCodec.encode re-runs the
 * Python encoder, which raises its own precise validation errors.
 * Byte-for-byte equality with the Python encoder is asserted in
 * tests/test_native_ext.py. */

typedef struct {
  uint8_t *p;
  Py_ssize_t len;
  Py_ssize_t cap;
  int oom;
} WBuf;

static int wb_reserve(WBuf *w, Py_ssize_t extra) {
  if (w->len + extra <= w->cap) return 1;
  Py_ssize_t ncap = w->cap ? w->cap * 2 : 256;
  while (ncap < w->len + extra) ncap *= 2;
  uint8_t *np = (uint8_t *)PyMem_Realloc(w->p, ncap);
  if (np == NULL) {
    w->oom = 1;
    return 0;
  }
  w->p = np;
  w->cap = ncap;
  return 1;
}

static void wr_i32(WBuf *w, int32_t v) {
  if (!wb_reserve(w, 4)) return;
  w->p[w->len++] = (uint8_t)(v >> 24);
  w->p[w->len++] = (uint8_t)(v >> 16);
  w->p[w->len++] = (uint8_t)(v >> 8);
  w->p[w->len++] = (uint8_t)v;
}

static void wr_i64(WBuf *w, int64_t v) {
  if (!wb_reserve(w, 8)) return;
  for (int i = 7; i >= 0; --i) w->p[w->len++] = (uint8_t)(v >> (8 * i));
}

/* fetch pkt[key] as int64 within [lo, hi]; 0 on any mismatch */
static int get_i64(PyObject *pkt, PyObject *key, int64_t lo, int64_t hi,
                   int64_t *out) {
  PyObject *v = PyDict_GetItemWithError(pkt, key); /* borrowed */
  if (v == NULL) {
    PyErr_Clear();
    return 0;
  }
  int overflow = 0;
  long long ll = PyLong_AsLongLongAndOverflow(v, &overflow);
  if (overflow || (ll == -1 && PyErr_Occurred())) {
    PyErr_Clear();
    return 0;
  }
  if (ll < lo || ll > hi) return 0;
  *out = ll;
  return 1;
}

/* write an int-length-prefixed utf8 string (the "" -> length -1
 * empty-buffer convention of JuteWriter.write_ustring) */
static int wr_str_obj(WBuf *w, PyObject *v) {
  if (!PyUnicode_Check(v)) return 0;
  Py_ssize_t n;
  const char *s = PyUnicode_AsUTF8AndSize(v, &n);
  if (s == NULL) {
    PyErr_Clear();
    return 0;
  }
  if (n > INT32_MAX) return 0;
  wr_i32(w, n == 0 ? -1 : (int32_t)n);
  if (n && wb_reserve(w, n)) {
    memcpy(w->p + w->len, s, n);
    w->len += n;
  }
  return 1;
}

static int wr_str_field(WBuf *w, PyObject *pkt, PyObject *key) {
  PyObject *v = PyDict_GetItemWithError(pkt, key);
  if (v == NULL) {
    PyErr_Clear();
    return 0;
  }
  return wr_str_obj(w, v);
}

/* write an int-length-prefixed byte buffer from pkt[key]
 * (empty -> length -1, lib/jute-buffer.js:127-130) */
static int wr_bytes_field(WBuf *w, PyObject *pkt, PyObject *key) {
  PyObject *v = PyDict_GetItemWithError(pkt, key);
  if (v == NULL || !PyBytes_Check(v)) {
    PyErr_Clear();
    return 0;
  }
  Py_ssize_t n = PyBytes_GET_SIZE(v);
  if (n > INT32_MAX) return 0;
  wr_i32(w, n == 0 ? -1 : (int32_t)n);
  if (n && wb_reserve(w, n)) {
    memcpy(w->p + w->len, PyBytes_AS_STRING(v), n);
    w->len += n;
  }
  return 1;
}

/* Stat from pkt[key] (an 11-tuple of ints, records.Stat) */
static int wr_stat_field(WBuf *w, PyObject *pkt, PyObject *key) {
  PyObject *v = PyDict_GetItemWithError(pkt, key);
  if (v == NULL || !PyTuple_Check(v) || PyTuple_GET_SIZE(v) != 11) {
    PyErr_Clear();
    return 0;
  }
  static const int widths[11] = {8, 8, 8, 8, 4, 4, 4, 8, 4, 4, 8};
  for (int i = 0; i < 11; ++i) {
    PyObject *f = PyTuple_GET_ITEM(v, i);
    int overflow = 0;
    long long ll = PyLong_AsLongLongAndOverflow(f, &overflow);
    if (overflow || (ll == -1 && PyErr_Occurred())) {
      PyErr_Clear();
      return 0;
    }
    if (widths[i] == 4) {
      if (ll < INT32_MIN || ll > INT32_MAX) return 0;
      wr_i32(w, (int32_t)ll);
    } else {
      wr_i64(w, ll);
    }
  }
  return 1;
}

/* name -> enum int via a reverse dict; -1 on miss */
static int rev_lookup(PyObject *dict, PyObject *name, int64_t *out) {
  PyObject *v = PyDict_GetItemWithError(dict, name);
  if (v == NULL) {
    PyErr_Clear();
    return 0;
  }
  long long ll = PyLong_AsLongLong(v);
  if (ll == -1 && PyErr_Occurred()) {
    PyErr_Clear();
    return 0;
  }
  *out = ll;
  return 1;
}

static PyObject *g_err_codes;   /* dict str -> int (reverse ErrCode) */
static PyObject *g_notif_codes; /* dict str -> int */
static PyObject *g_state_codes; /* dict str -> int */
static PyObject *g_op_codes;    /* dict str -> int (full OpCode) */

/* response body by layout; 1 ok, 0 -> fall back to Python */
static int enc_resp_body(WBuf *w, PyObject *pkt, int layout) {
  switch (layout) {
    case LAYOUT_EMPTY:
      return 1;
    case LAYOUT_CREATE:
      return wr_str_field(w, pkt, s_path);
    case LAYOUT_STAT_ONLY:
      return wr_stat_field(w, pkt, s_stat);
    case LAYOUT_GET_DATA:
      return wr_bytes_field(w, pkt, s_data)
             && wr_stat_field(w, pkt, s_stat);
    case LAYOUT_GET_CHILDREN:
    case LAYOUT_GET_CHILDREN2: {
      PyObject *lst = PyDict_GetItemWithError(pkt, s_children);
      if (lst == NULL || !PyList_Check(lst)) {
        PyErr_Clear();
        return 0;
      }
      Py_ssize_t n = PyList_GET_SIZE(lst);
      if (n > INT32_MAX) return 0;
      wr_i32(w, (int32_t)n);
      for (Py_ssize_t i = 0; i < n; ++i) {
        if (!wr_str_obj(w, PyList_GET_ITEM(lst, i))) return 0;
      }
      if (layout == LAYOUT_GET_CHILDREN2)
        return wr_stat_field(w, pkt, s_stat);
      return 1;
    }
    case LAYOUT_NOTIFICATION: {
      PyObject *t = PyDict_GetItemWithError(pkt, s_type);
      PyObject *st = t ? PyDict_GetItemWithError(pkt, s_state) : NULL;
      int64_t tv, sv;
      if (st == NULL || !rev_lookup(g_notif_codes, t, &tv)
          || !rev_lookup(g_state_codes, st, &sv)) {
        PyErr_Clear();
        return 0;
      }
      wr_i32(w, (int32_t)tv);
      wr_i32(w, (int32_t)sv);
      return wr_str_field(w, pkt, s_path);
    }
    default: /* GET_ACL responses are rare; Python handles them */
      return 0;
  }
}

/* request body by layout; 1 ok, 0 -> fall back */
static int enc_req_body(WBuf *w, PyObject *pkt, int layout) {
  switch (layout) {
    case RQ_EMPTY:
      return 1;
    case RQ_PATH:
      return wr_str_field(w, pkt, s_path);
    case RQ_PATH_WATCH: {
      if (!wr_str_field(w, pkt, s_path)) return 0;
      PyObject *v = PyDict_GetItemWithError(pkt, s_watch);
      if (v == NULL || !PyBool_Check(v)) {
        PyErr_Clear();
        return 0;
      }
      if (wb_reserve(w, 1)) w->p[w->len++] = v == Py_True ? 1 : 0;
      return 1;
    }
    case RQ_DELETE: {
      int64_t ver;
      if (!wr_str_field(w, pkt, s_path)
          || !get_i64(pkt, s_version, INT32_MIN, INT32_MAX, &ver))
        return 0;
      wr_i32(w, (int32_t)ver);
      return 1;
    }
    case RQ_SET_DATA: {
      int64_t ver;
      if (!wr_str_field(w, pkt, s_path)
          || !wr_bytes_field(w, pkt, s_data)
          || !get_i64(pkt, s_version, INT32_MIN, INT32_MAX, &ver))
        return 0;
      wr_i32(w, (int32_t)ver);
      return 1;
    }
    case RQ_CREATE: {
      /* path, data, ACL list (count; perms/scheme/id per entry —
       * records.write_acl), flags (CreateFlag coerces; default 0) */
      if (!wr_str_field(w, pkt, s_path)
          || !wr_bytes_field(w, pkt, s_data))
        return 0;
      PyObject *acl = PyDict_GetItemWithError(pkt, s_acl);
      if (acl == NULL || !(PyList_Check(acl) || PyTuple_Check(acl))) {
        PyErr_Clear();
        return 0;
      }
      Py_INCREF(acl); /* GetAttr below may run arbitrary Python that
                       * drops the packet's reference */
      Py_ssize_t n = PySequence_Fast_GET_SIZE(acl);
      if (n > INT32_MAX) {
        Py_DECREF(acl);
        return 0;
      }
      wr_i32(w, (int32_t)n);
      for (Py_ssize_t i = 0; i < n; ++i) {
        /* a list can shrink under a hostile __getattr__ */
        if (i >= PySequence_Fast_GET_SIZE(acl)) {
          Py_DECREF(acl);
          return 0;
        }
        PyObject *entry = PySequence_Fast_GET_ITEM(acl, i);
        Py_INCREF(entry);
        PyObject *perms = PyObject_GetAttr(entry, s_perms);
        PyObject *idobj = perms ? PyObject_GetAttr(entry, s_id_attr)
                                : NULL;
        PyObject *scheme = idobj ? PyObject_GetAttr(idobj, s_scheme)
                                 : NULL;
        PyObject *ident = scheme ? PyObject_GetAttr(idobj, s_id_attr)
                                 : NULL;
        int ok = 0;
        if (ident != NULL) {
          int overflow = 0;
          long long pv = PyLong_AsLongLongAndOverflow(perms, &overflow);
          if (!overflow && !(pv == -1 && PyErr_Occurred())
              && pv >= INT32_MIN && pv <= INT32_MAX) {
            wr_i32(w, (int32_t)pv);
            ok = wr_str_obj(w, scheme) && wr_str_obj(w, ident);
          }
        }
        PyErr_Clear();
        Py_XDECREF(perms);
        Py_XDECREF(idobj);
        Py_XDECREF(scheme);
        Py_XDECREF(ident);
        Py_DECREF(entry);
        if (!ok) {
          Py_DECREF(acl);
          return 0;
        }
      }
      Py_DECREF(acl);
      /* flags: missing defaults to 0; negatives fall back — the
       * Python spec normalizes them through CreateFlag (e.g. -1
       * becomes 3), which the verbatim C write would diverge from */
      int64_t flags = 0;
      PyObject *fv = PyDict_GetItemWithError(pkt, s_flags);
      if (fv != NULL) {
        int overflow = 0;
        long long ll = PyLong_AsLongLongAndOverflow(fv, &overflow);
        if (overflow || (ll == -1 && PyErr_Occurred())) {
          PyErr_Clear();
          return 0;
        }
        if (ll < 0 || ll > INT32_MAX) return 0;
        flags = ll;
      } else {
        PyErr_Clear();
      }
      wr_i32(w, (int32_t)flags);
      return 1;
    }
    case RQ_ADD_WATCH: {
      /* only the two defined AddWatchMode values encode verbatim;
       * anything else falls back so the Python spec raises its own
       * validation error */
      int64_t mode;
      if (!wr_str_field(w, pkt, s_path)
          || !get_i64(pkt, s_mode, 0, 1, &mode))
        return 0;
      wr_i32(w, (int32_t)mode);
      return 1;
    }
    default: /* SET_WATCHES/2 are resume-time-rare; Python handles them */
      return 0;
  }
}

/* shared: header + body + length prefix -> bytes (or NULL=fall back) */
static PyObject *encode_framed(PyObject *pkt, int is_request) {
  WBuf w = {NULL, 0, 0, 0};
  wr_i32(&w, 0); /* length prefix slot */

  int64_t xid;
  if (!get_i64(pkt, s_xid, INT32_MIN, INT32_MAX, &xid)) goto fallback;
  wr_i32(&w, (int32_t)xid);

  PyObject *op = PyDict_GetItemWithError(pkt, s_opcode);
  if (op == NULL || !PyUnicode_Check(op)) {
    PyErr_Clear();
    goto fallback;
  }

  if (is_request) {
    int64_t opnum;
    PyObject *entry;
    if (!rev_lookup(g_op_codes, op, &opnum)) goto fallback;
    wr_i32(&w, (int32_t)opnum);
    /* layout via the request table (keyed by opcode number) */
    entry = int_key_get(g_req_opcodes, opnum);
    if (entry == NULL) goto fallback;
    if (!enc_req_body(&w, pkt,
                      (int)PyLong_AsLong(PyTuple_GET_ITEM(entry, 1))))
      goto fallback;
  } else {
    int64_t zxid, errnum = 0;
    if (!get_i64(pkt, s_zxid, INT64_MIN, INT64_MAX, &zxid))
      goto fallback;
    wr_i64(&w, zxid);
    PyObject *err = PyDict_GetItemWithError(pkt, s_err);
    if (err == NULL) { /* write_response defaults missing err to OK */
      PyErr_Clear();
    } else if (!rev_lookup(g_err_codes, err, &errnum)) {
      goto fallback;
    }
    wr_i32(&w, (int32_t)errnum);
    if (errnum == 0) {
      PyObject *layout = PyDict_GetItemWithError(g_layouts, op);
      if (layout == NULL) {
        PyErr_Clear();
        goto fallback;
      }
      if (!enc_resp_body(&w, pkt, (int)PyLong_AsLong(layout)))
        goto fallback;
    }
  }

  if (w.oom) goto fallback;
  if (w.len - 4 > INT32_MAX) goto fallback; /* Python raises properly */
  {
    int32_t body_len = (int32_t)(w.len - 4);
    w.p[0] = (uint8_t)(body_len >> 24);
    w.p[1] = (uint8_t)(body_len >> 16);
    w.p[2] = (uint8_t)(body_len >> 8);
    w.p[3] = (uint8_t)body_len;
    PyObject *out =
        PyBytes_FromStringAndSize((const char *)w.p, w.len);
    PyMem_Free(w.p);
    return out; /* NULL here means real OOM; exception is set */
  }

fallback:
  PyMem_Free(w.p);
  Py_RETURN_NONE; /* sentinel: caller uses the Python encoder */
}

static PyObject *py_encode_request(PyObject *self, PyObject *args) {
  PyObject *pkt;
  if (!PyArg_ParseTuple(args, "O!", &PyDict_Type, &pkt)) return NULL;
  if (g_op_codes == NULL) {
    PyErr_SetString(PyExc_RuntimeError, "setup() not called");
    return NULL;
  }
  return encode_framed(pkt, 1);
}

static PyObject *py_encode_response(PyObject *self, PyObject *args) {
  PyObject *pkt;
  if (!PyArg_ParseTuple(args, "O!", &PyDict_Type, &pkt)) return NULL;
  if (g_op_codes == NULL) {
    PyErr_SetString(PyExc_RuntimeError, "setup() not called");
    return NULL;
  }
  return encode_framed(pkt, 0);
}

/* ---- module functions ---- */

static PyObject *py_setup(PyObject *self, PyObject *args) {
  PyObject *stat_cls, *acl_cls, *id_cls, *perm_cls, *create_flag_cls,
      *err_names, *notif_types, *states, *layouts, *req_opcodes,
      *op_names, *err_codes, *notif_codes, *state_codes, *op_codes;
  if (!PyArg_ParseTuple(args, "OOOOOOOOOOOOOOO", &stat_cls, &acl_cls,
                        &id_cls, &perm_cls, &create_flag_cls,
                        &err_names, &notif_types, &states, &layouts,
                        &req_opcodes, &op_names, &err_codes,
                        &notif_codes, &state_codes, &op_codes))
    return NULL;
  /* rd_stat builds instances through tuple's tp_new */
  if (!PyType_Check(stat_cls) ||
      !PyType_IsSubtype((PyTypeObject *)stat_cls, &PyTuple_Type)) {
    PyErr_SetString(PyExc_TypeError, "Stat must be a tuple subclass");
    return NULL;
  }
  Py_INCREF(stat_cls); Py_XSETREF(g_stat_cls, stat_cls);
  Py_INCREF(acl_cls); Py_XSETREF(g_acl_cls, acl_cls);
  Py_INCREF(id_cls); Py_XSETREF(g_id_cls, id_cls);
  Py_INCREF(perm_cls); Py_XSETREF(g_perm_cls, perm_cls);
  Py_INCREF(create_flag_cls);
  Py_XSETREF(g_create_flag_cls, create_flag_cls);
  Py_INCREF(err_names); Py_XSETREF(g_err_names, err_names);
  Py_INCREF(notif_types); Py_XSETREF(g_notif_types, notif_types);
  Py_INCREF(states); Py_XSETREF(g_states, states);
  Py_INCREF(layouts); Py_XSETREF(g_layouts, layouts);
  Py_INCREF(req_opcodes); Py_XSETREF(g_req_opcodes, req_opcodes);
  Py_INCREF(op_names); Py_XSETREF(g_op_names, op_names);
  Py_INCREF(err_codes); Py_XSETREF(g_err_codes, err_codes);
  Py_INCREF(notif_codes); Py_XSETREF(g_notif_codes, notif_codes);
  Py_INCREF(state_codes); Py_XSETREF(g_state_codes, state_codes);
  Py_INCREF(op_codes); Py_XSETREF(g_op_codes, op_codes);
  Py_RETURN_NONE;
}

/* shared frame walk: slice complete frames, decode each body via the
 * reply (xid_map != NULL) or request decoder, with the PacketCodec
 * error contract.  Consumes/releases `view`. */
static PyObject *decode_stream(Py_buffer view, PyObject *xid_map,
                               int max_packet) {
  const char *what = xid_map != NULL ? "Response" : "Request";
  if (g_stat_cls == NULL) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_RuntimeError, "setup() not called");
    return NULL;
  }

  const uint8_t *buf = (const uint8_t *)view.buf;
  Py_ssize_t len = view.len;

  PyObject *pkts = PyList_New(0);
  if (pkts == NULL) {
    PyBuffer_Release(&view);
    return NULL;
  }

  const char *err_kind = NULL;
  char err_msg[256] = {0};
  Py_ssize_t consumed = 0;

  /* pass 1: frame boundaries (so a bad prefix drops earlier frames
   * exactly like FrameDecoder.feed raising mid-scan) */
  Py_ssize_t off = 0, end_of_frames = 0;
  while (len - off >= 4) {
    int32_t ln = (int32_t)(((uint32_t)buf[off] << 24) |
                           ((uint32_t)buf[off + 1] << 16) |
                           ((uint32_t)buf[off + 2] << 8) |
                           (uint32_t)buf[off + 3]);
    if (ln < 0 || ln > max_packet) {
      err_kind = "BAD_LENGTH";
      snprintf(err_msg, sizeof(err_msg), "Invalid ZK packet length %d",
               ln);
      consumed = off;
      goto done;
    }
    if (len - off < 4 + (Py_ssize_t)ln) break;
    off += 4 + ln;
    end_of_frames = off;
  }
  consumed = end_of_frames;

  /* pass 2: decode each frame body */
  off = 0;
  while (off < end_of_frames) {
    int32_t ln = (int32_t)(((uint32_t)buf[off] << 24) |
                           ((uint32_t)buf[off + 1] << 16) |
                           ((uint32_t)buf[off + 2] << 8) |
                           (uint32_t)buf[off + 3]);
    Cursor c = {buf + off + 4, ln, 0, {0}};
    PyObject *pkt = xid_map != NULL ? decode_reply(&c, xid_map)
                                    : decode_request(&c);
    if (pkt == NULL) {
      if (PyErr_Occurred()) { /* real exception (OOM etc.) */
        Py_DECREF(pkts);
        PyBuffer_Release(&view);
        return NULL;
      }
      if (c.unsupported) {
        /* valid frame, no layout in this tier: leave it (and
         * everything after it) in the buffer for the Python spec
         * tier — consumed stops at the frame boundary */
        err_kind = "UNSUPPORTED";
        snprintf(err_msg, sizeof(err_msg), "%s", c.err);
        consumed = off;
        goto done;
      }
      err_kind = "BAD_DECODE";
      snprintf(err_msg, sizeof(err_msg), "Failed to decode %s: %s",
               what, c.err);
      goto done;
    }
    if (PyList_Append(pkts, pkt) < 0) {
      Py_DECREF(pkt);
      Py_DECREF(pkts);
      PyBuffer_Release(&view);
      return NULL;
    }
    Py_DECREF(pkt);
    off += 4 + ln;
  }

done:
  PyBuffer_Release(&view);
  PyObject *ret =
      err_kind == NULL
          ? Py_BuildValue("(OnOO)", pkts, consumed, Py_None, Py_None)
          : Py_BuildValue("(Onss)", pkts, consumed, err_kind, err_msg);
  Py_DECREF(pkts); /* BuildValue's "O" took its own reference */
  return ret;
}

static PyObject *py_decode_responses(PyObject *self, PyObject *args) {
  Py_buffer view;
  PyObject *xid_map;
  int max_packet;
  if (!PyArg_ParseTuple(args, "y*O!i", &view, &PyDict_Type, &xid_map,
                        &max_packet))
    return NULL;
  return decode_stream(view, xid_map, max_packet);
}

static PyObject *py_decode_requests(PyObject *self, PyObject *args) {
  Py_buffer view;
  int max_packet;
  if (!PyArg_ParseTuple(args, "y*i", &view, &max_packet)) return NULL;
  return decode_stream(view, NULL, max_packet);
}

static PyObject *py_abi_version(PyObject *self, PyObject *noargs) {
  return PyLong_FromLong(10);
}

/* CRC32C (Castagnoli, reflected 0x82F63B78) for the write-ahead-log
 * record framing (zkstream_tpu/server/persist.py).  Table-driven and
 * portable; the pure-Python table walk is the spec and the fallback,
 * A/B-tested equal in tests/test_wal.py.  ~60x the Python loop on
 * the ~100-byte record bodies the WAL appends per committed txn. */
static uint32_t crc32c_table[256];

static void crc32c_table_init(void) {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
    crc32c_table[i] = c;
  }
}

static PyObject *py_crc32c(PyObject *self, PyObject *args) {
  Py_buffer buf;
  unsigned int seed = 0;
  if (!PyArg_ParseTuple(args, "y*|I", &buf, &seed)) return NULL;
  uint32_t c = seed ^ 0xFFFFFFFFu;
  const unsigned char *p = (const unsigned char *)buf.buf;
  Py_ssize_t n = buf.len;
  for (Py_ssize_t i = 0; i < n; i++)
    c = crc32c_table[(c ^ p[i]) & 0xFFu] ^ (c >> 8);
  PyBuffer_Release(&buf);
  return PyLong_FromUnsignedLong(c ^ 0xFFFFFFFFu);
}

/* ---- batched-syscall transport tier (io/transport.py) ----------------
 *
 * The deferred join-and-write boundary of the outbound plane: one C
 * call per corked tick takes every dirty connection's frame list and
 * moves the bytes to the kernel without materializing an intermediate
 * joined Python bytes per connection.
 *
 *   submit_writev(fds, chunklists)     parallel arrays: fds[i] gets
 *     -> [written_or_negative_errno, ...]   chunklists[i]; one
 *        writev(2) per entry (vectored: the "join" is the iovec
 *        array; flat arrays skip a tuple per entry on the hot path)
 *
 *   uring_create(depth) -> capsule          io_uring ring, or OSError
 *   uring_submit(capsule, fds, chunklists)
 *     -> ([sent_or_negative_errno, ...], enter_syscalls)
 *        ONE chained SQE submission (IORING_OP_SENDMSG + MSG_DONTWAIT
 *        per entry, iovec-joined) covering the whole batch; the call
 *        submits and reaps synchronously, so buffer lifetimes are the
 *        caller's references and per-fd ordering is submission order.
 *   uring_close(capsule)
 *
 * The Python tier (io/transport.py) holds the fallback loop
 * (os.writev per entry) and the capability probe; CPython ignores
 * SIGPIPE, so a peer-reset socket surfaces as -EPIPE in the result
 * slot, never a signal. */

#define ZK_IOV_CAP 1024 /* IOV_MAX floor: writev waves per entry */

#ifndef MSG_NOSIGNAL
#define MSG_NOSIGNAL 0
#endif

/* One entry's vectored write: returns bytes written, or -errno when
 * nothing was written.  Partial waves stop the loop (the caller
 * re-routes the remainder through the asyncio transport).  The
 * single-chunk case — the fan-out shape: one pre-joined notification
 * batch per connection — takes send(2), which skips the kernel's
 * iovec import; non-sockets fall through to writev. */
static long long writev_chunks(int fd, struct iovec *iov,
                               Py_ssize_t nch) {
  if (nch == 1) {
    ssize_t r;
    do {
      r = send(fd, iov[0].iov_base, iov[0].iov_len, MSG_NOSIGNAL);
    } while (r < 0 && errno == EINTR);
    if (r >= 0) return (long long)r;
    if (errno != ENOTSOCK) return -(long long)errno;
  }
  long long written = 0;
  Py_ssize_t base = 0;
  while (base < nch) {
    int cnt = (nch - base) > ZK_IOV_CAP ? ZK_IOV_CAP
                                        : (int)(nch - base);
    ssize_t r;
    do {
      r = writev(fd, iov + base, cnt);
    } while (r < 0 && errno == EINTR);
    if (r < 0) {
      if (written == 0) return -(long long)errno;
      break;
    }
    written += (long long)r;
    long long wave = 0;
    for (int k = 0; k < cnt; k++)
      wave += (long long)iov[base + k].iov_len;
    if ((long long)r < wave) break;
    base += cnt;
  }
  return written;
}

/* Acquire one entry's chunk list as (Py_buffer[], iovec[]).  Returns
 * the chunk count, or -1 with a Python error set.  *bufs_out buffers
 * are acquired [0, count) and must be released by the caller. */
static Py_ssize_t acquire_iov(PyObject *chunks, Py_buffer **bufs_out,
                              struct iovec **iov_out,
                              PyObject **fast_out) {
  PyObject *cf = PySequence_Fast(chunks, "chunks must be a sequence");
  if (!cf) return -1;
  Py_ssize_t nch = PySequence_Fast_GET_SIZE(cf);
  Py_buffer *bufs = PyMem_Malloc(sizeof(Py_buffer) * (nch ? nch : 1));
  struct iovec *iov =
      PyMem_Malloc(sizeof(struct iovec) * (nch ? nch : 1));
  if (!bufs || !iov) {
    PyMem_Free(bufs);
    PyMem_Free(iov);
    Py_DECREF(cf);
    PyErr_NoMemory();
    return -1;
  }
  for (Py_ssize_t j = 0; j < nch; j++) {
    if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(cf, j), &bufs[j],
                           PyBUF_SIMPLE) < 0) {
      while (j-- > 0) PyBuffer_Release(&bufs[j]);
      PyMem_Free(bufs);
      PyMem_Free(iov);
      Py_DECREF(cf);
      return -1;
    }
    iov[j].iov_base = bufs[j].buf;
    iov[j].iov_len = (size_t)bufs[j].len;
  }
  *bufs_out = bufs;
  *iov_out = iov;
  *fast_out = cf;
  return nch;
}

static void release_iov(Py_buffer *bufs, struct iovec *iov,
                        PyObject *fast, Py_ssize_t nch) {
  for (Py_ssize_t j = 0; j < nch; j++) PyBuffer_Release(&bufs[j]);
  PyMem_Free(bufs);
  PyMem_Free(iov);
  Py_DECREF(fast);
}

/* Chunk counts per connection per tick are tiny in steady state (a
 * corked tick's frames arrive as ONE pre-joined plane flush, a
 * fan-out adds one more): a stack-resident iovec covers the common
 * case with zero allocation per connection. */
#define ZK_STACK_IOV 8

/* Fetch entry i of the parallel (fds, chunklists) batch arrays.
 * Returns 0 on success with *fd_out / *chunks_out set, -1 with a
 * Python error set. */
static int batch_entry(PyObject *fds, PyObject *chunklists,
                       Py_ssize_t i, int *fd_out,
                       PyObject **chunks_out) {
  long fd = PyLong_AsLong(PySequence_Fast_GET_ITEM(fds, i));
  if (fd == -1 && PyErr_Occurred()) return -1;
  *fd_out = (int)fd;
  *chunks_out = PySequence_Fast_GET_ITEM(chunklists, i);
  return 0;
}

static PyObject *py_submit_writev(PyObject *self, PyObject *args) {
  PyObject *fds_obj, *cl_obj;
  if (!PyArg_ParseTuple(args, "OO", &fds_obj, &cl_obj)) return NULL;
  PyObject *fast = PySequence_Fast(fds_obj, "fds must be a sequence");
  if (!fast) return NULL;
  PyObject *clfast =
      PySequence_Fast(cl_obj, "chunklists must be a sequence");
  if (!clfast) {
    Py_DECREF(fast);
    return NULL;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  if (PySequence_Fast_GET_SIZE(clfast) != n) {
    PyErr_SetString(PyExc_ValueError, "fds/chunklists length mismatch");
    Py_DECREF(fast);
    Py_DECREF(clfast);
    return NULL;
  }
  PyObject *results = PyList_New(n);
  if (!results) {
    Py_DECREF(fast);
    Py_DECREF(clfast);
    return NULL;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    int fd;
    PyObject *chunks;
    if (batch_entry(fast, clfast, i, &fd, &chunks) < 0) goto fail;
    Py_buffer sbufs[ZK_STACK_IOV];
    struct iovec siov[ZK_STACK_IOV];
    Py_buffer *bufs = sbufs;
    struct iovec *iov = siov;
    PyObject *cf;
    Py_ssize_t nch;
    if (PyList_CheckExact(chunks)
        && PyList_GET_SIZE(chunks) <= ZK_STACK_IOV) {
      /* the hot path: small chunk list, stack arrays, no mallocs */
      nch = PyList_GET_SIZE(chunks);
      cf = NULL;
      Py_ssize_t j;
      for (j = 0; j < nch; j++) {
        if (PyObject_GetBuffer(PyList_GET_ITEM(chunks, j), &bufs[j],
                               PyBUF_SIMPLE) < 0)
          break;
        iov[j].iov_base = bufs[j].buf;
        iov[j].iov_len = (size_t)bufs[j].len;
      }
      if (j < nch) {
        while (j-- > 0) PyBuffer_Release(&bufs[j]);
        goto fail;
      }
    } else {
      nch = acquire_iov(chunks, &bufs, &iov, &cf);
      if (nch < 0) goto fail;
    }
    long long res = nch ? writev_chunks(fd, iov, nch) : 0;
    if (cf != NULL) {
      release_iov(bufs, iov, cf, nch);
    } else {
      for (Py_ssize_t j = 0; j < nch; j++) PyBuffer_Release(&bufs[j]);
    }
    PyObject *val = PyLong_FromLongLong(res);
    if (!val) goto fail;
    PyList_SET_ITEM(results, i, val);
  }
  Py_DECREF(fast);
  Py_DECREF(clfast);
  return results;
fail:
  Py_DECREF(fast);
  Py_DECREF(clfast);
  Py_DECREF(results);
  return NULL;
}

/* ---- batched receive drain (io/ingress.py) --------------------------
 *
 * The receive-direction twin of submit_writev: one C call per dirty
 * ingress shard per tick takes the shard's readable fds and moves
 * every connection's pending bytes out of the kernel — one recv(2)
 * per fd inside the call (TCP has no cross-fd recvmmsg; the Python-
 * level submission count is what drops to O(dirty shards)), zero
 * per-fd Python dispatch, zero intermediate buffers.
 *
 *   drain_recv(fds, bufsize)
 *     -> [bytes | -errno, ...]   per fd: the received bytes (b'' =
 *        EOF, exactly what a StreamReader read returns at EOF), or
 *        a negative errno (-EAGAIN = readiness raced an earlier
 *        drain; the caller skips, never closes).
 *
 * Buffers are allocated at bufsize and resized down to the received
 * length — the common short read costs one shrink, never a copy of
 * bytes that were not received. */

static PyObject *py_drain_recv(PyObject *self, PyObject *args) {
  PyObject *fds_obj;
  int bufsize;
  if (!PyArg_ParseTuple(args, "Oi", &fds_obj, &bufsize)) return NULL;
  if (bufsize <= 0) {
    PyErr_SetString(PyExc_ValueError, "bufsize must be positive");
    return NULL;
  }
  PyObject *fast = PySequence_Fast(fds_obj, "fds must be a sequence");
  if (!fast) return NULL;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject *results = PyList_New(n);
  if (!results) {
    Py_DECREF(fast);
    return NULL;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    long fd = PyLong_AsLong(PySequence_Fast_GET_ITEM(fast, i));
    if (fd == -1 && PyErr_Occurred()) goto fail;
    PyObject *buf = PyBytes_FromStringAndSize(NULL, bufsize);
    if (!buf) goto fail;
    ssize_t r;
    do {
      r = recv((int)fd, PyBytes_AS_STRING(buf), (size_t)bufsize,
               MSG_DONTWAIT);
    } while (r < 0 && errno == EINTR);
    if (r < 0 && errno == ENOTSOCK) {
      /* non-socket fd (test double over a pipe): plain read — the
       * caller's fds are already non-blocking */
      do {
        r = read((int)fd, PyBytes_AS_STRING(buf), (size_t)bufsize);
      } while (r < 0 && errno == EINTR);
    }
    if (r < 0) {
      Py_DECREF(buf);
      PyObject *val = PyLong_FromLong(-(long)errno);
      if (!val) goto fail;
      PyList_SET_ITEM(results, i, val);
      continue;
    }
    if (r < (ssize_t)bufsize && _PyBytes_Resize(&buf, r) < 0)
      goto fail;
    PyList_SET_ITEM(results, i, buf);
  }
  Py_DECREF(fast);
  return results;
fail:
  Py_DECREF(fast);
  Py_DECREF(results);
  return NULL;
}

#ifdef __linux__

/* io_uring ABI, declared locally: this image's kernel headers may
 * predate io_uring entirely (the runtime probe decides availability,
 * the build must always succeed).  Layouts are the stable v5.1 ABI. */

#ifndef __NR_io_uring_setup
#define __NR_io_uring_setup 425
#endif
#ifndef __NR_io_uring_enter
#define __NR_io_uring_enter 426
#endif

#define ZK_IORING_OFF_SQ_RING 0ULL
#define ZK_IORING_OFF_CQ_RING 0x8000000ULL
#define ZK_IORING_OFF_SQES 0x10000000ULL
#define ZK_IORING_ENTER_GETEVENTS 1u
#define ZK_IORING_FEAT_SINGLE_MMAP 1u
#define ZK_IORING_OP_SENDMSG 9

#ifndef MSG_NOSIGNAL
#define MSG_NOSIGNAL 0
#endif

struct zk_sqring_offsets {
  uint32_t head, tail, ring_mask, ring_entries, flags, dropped, array,
      resv1;
  uint64_t resv2;
};

struct zk_cqring_offsets {
  uint32_t head, tail, ring_mask, ring_entries, overflow, cqes;
  uint64_t resv[2];
};

struct zk_uring_params {
  uint32_t sq_entries, cq_entries, flags, sq_thread_cpu,
      sq_thread_idle, features, wq_fd, resv[3];
  struct zk_sqring_offsets sq_off;
  struct zk_cqring_offsets cq_off;
};

struct zk_sqe { /* 64 bytes */
  uint8_t opcode, flags;
  uint16_t ioprio;
  int32_t fd;
  uint64_t off;
  uint64_t addr;
  uint32_t len;
  uint32_t msg_flags;
  uint64_t user_data;
  uint64_t pad[3];
};

struct zk_cqe {
  uint64_t user_data;
  int32_t res;
  uint32_t flags;
};

typedef struct {
  int ring_fd;
  uint64_t gen; /* submission generation: stamps user_data so a CQE
                 * from an abandoned wave (enter failure after partial
                 * completion) can never be attributed to a later
                 * wave's entry */
  unsigned sq_entries, cq_entries;
  unsigned char *sq_ptr;
  size_t sq_sz;
  unsigned char *cq_ptr;
  size_t cq_sz;
  int single_mmap;
  struct zk_sqe *sqes;
  size_t sqes_sz;
  unsigned *sq_head, *sq_tail, *sq_mask, *sq_array;
  unsigned *cq_head, *cq_tail, *cq_mask;
  struct zk_cqe *cqarr;
} zk_uring;

static void uring_free(zk_uring *u) {
  if (!u) return;
  if (u->sq_ptr && u->sq_ptr != MAP_FAILED) munmap(u->sq_ptr, u->sq_sz);
  if (!u->single_mmap && u->cq_ptr && u->cq_ptr != MAP_FAILED)
    munmap(u->cq_ptr, u->cq_sz);
  if (u->sqes && (void *)u->sqes != MAP_FAILED)
    munmap(u->sqes, u->sqes_sz);
  if (u->ring_fd >= 0) close(u->ring_fd);
  PyMem_Free(u);
}

static zk_uring uring_closed; /* sentinel: ring explicitly closed */

static void uring_capsule_destroy(PyObject *cap) {
  zk_uring *u = PyCapsule_GetPointer(cap, "zkwire.uring");
  if (u && u != &uring_closed) uring_free(u);
}

static PyObject *py_uring_create(PyObject *self, PyObject *args) {
  unsigned depth = 256;
  if (!PyArg_ParseTuple(args, "|I", &depth)) return NULL;
  struct zk_uring_params p;
  memset(&p, 0, sizeof(p));
  int fd = (int)syscall(__NR_io_uring_setup, depth, &p);
  if (fd < 0) return PyErr_SetFromErrno(PyExc_OSError);
  zk_uring *u = PyMem_Calloc(1, sizeof(zk_uring));
  if (!u) {
    close(fd);
    return PyErr_NoMemory();
  }
  u->ring_fd = fd;
  u->sq_entries = p.sq_entries;
  u->cq_entries = p.cq_entries;
  u->sq_sz = p.sq_off.array + p.sq_entries * sizeof(unsigned);
  u->cq_sz = p.cq_off.cqes + p.cq_entries * sizeof(struct zk_cqe);
  u->single_mmap = (p.features & ZK_IORING_FEAT_SINGLE_MMAP) != 0;
  if (u->single_mmap) {
    if (u->cq_sz > u->sq_sz) u->sq_sz = u->cq_sz;
    u->cq_sz = u->sq_sz;
  }
  u->sq_ptr = mmap(NULL, u->sq_sz, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_POPULATE, fd, ZK_IORING_OFF_SQ_RING);
  u->cq_ptr = u->single_mmap
                  ? u->sq_ptr
                  : mmap(NULL, u->cq_sz, PROT_READ | PROT_WRITE,
                         MAP_SHARED | MAP_POPULATE, fd,
                         ZK_IORING_OFF_CQ_RING);
  u->sqes_sz = p.sq_entries * sizeof(struct zk_sqe);
  u->sqes = mmap(NULL, u->sqes_sz, PROT_READ | PROT_WRITE,
                 MAP_SHARED | MAP_POPULATE, fd, ZK_IORING_OFF_SQES);
  if (u->sq_ptr == MAP_FAILED || u->cq_ptr == MAP_FAILED ||
      (void *)u->sqes == MAP_FAILED) {
    PyErr_SetFromErrno(PyExc_OSError);
    uring_free(u);
    return NULL;
  }
  u->sq_head = (unsigned *)(u->sq_ptr + p.sq_off.head);
  u->sq_tail = (unsigned *)(u->sq_ptr + p.sq_off.tail);
  u->sq_mask = (unsigned *)(u->sq_ptr + p.sq_off.ring_mask);
  u->sq_array = (unsigned *)(u->sq_ptr + p.sq_off.array);
  u->cq_head = (unsigned *)(u->cq_ptr + p.cq_off.head);
  u->cq_tail = (unsigned *)(u->cq_ptr + p.cq_off.tail);
  u->cq_mask = (unsigned *)(u->cq_ptr + p.cq_off.ring_mask);
  u->cqarr = (struct zk_cqe *)(u->cq_ptr + p.cq_off.cqes);
  PyObject *cap =
      PyCapsule_New(u, "zkwire.uring", uring_capsule_destroy);
  if (!cap) uring_free(u);
  return cap;
}

static zk_uring *uring_from_capsule(PyObject *cap) {
  zk_uring *u = (zk_uring *)PyCapsule_GetPointer(cap, "zkwire.uring");
  if (u == &uring_closed) {
    PyErr_SetString(PyExc_ValueError, "uring already closed");
    return NULL;
  }
  return u;
}

static PyObject *py_uring_submit(PyObject *self, PyObject *args) {
  PyObject *cap, *fds_obj, *cl_obj;
  if (!PyArg_ParseTuple(args, "OOO", &cap, &fds_obj, &cl_obj))
    return NULL;
  zk_uring *u = uring_from_capsule(cap);
  if (!u) return NULL;
  PyObject *fast = PySequence_Fast(fds_obj, "fds must be a sequence");
  if (!fast) return NULL;
  PyObject *clfast =
      PySequence_Fast(cl_obj, "chunklists must be a sequence");
  if (!clfast) {
    Py_DECREF(fast);
    return NULL;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  if (PySequence_Fast_GET_SIZE(clfast) != n) {
    PyErr_SetString(PyExc_ValueError, "fds/chunklists length mismatch");
    Py_DECREF(fast);
    Py_DECREF(clfast);
    return NULL;
  }
  PyObject *results = PyList_New(n);
  if (!results) {
    Py_DECREF(fast);
    Py_DECREF(clfast);
    return NULL;
  }
  long enters = 0;
  Py_ssize_t done = 0;
  while (done < n) {
    Py_ssize_t wave = n - done;
    if (wave > (Py_ssize_t)u->sq_entries) wave = u->sq_entries;
    /* per-wave scratch: msghdr + acquired chunk buffers per entry */
    struct msghdr *msgs = PyMem_Calloc(wave, sizeof(struct msghdr));
    Py_buffer **bufsv = PyMem_Calloc(wave, sizeof(Py_buffer *));
    struct iovec **iovv = PyMem_Calloc(wave, sizeof(struct iovec *));
    PyObject **fastv = PyMem_Calloc(wave, sizeof(PyObject *));
    Py_ssize_t *nchv = PyMem_Calloc(wave, sizeof(Py_ssize_t));
    char *filled = PyMem_Calloc(wave, 1);
    if (!msgs || !bufsv || !iovv || !fastv || !nchv || !filled) {
      PyMem_Free(msgs);
      PyMem_Free(bufsv);
      PyMem_Free(iovv);
      PyMem_Free(fastv);
      PyMem_Free(nchv);
      PyMem_Free(filled);
      Py_DECREF(fast);
      Py_DECREF(clfast);
      Py_DECREF(results);
      return PyErr_NoMemory();
    }
    int bad = 0;
    int inflight = 0; /* wait-phase enter failure: submitted sends may
                       * still run — the kernel reads their iovecs and
                       * buffers, so the unreaped entries' resources
                       * must be LEAKED, never released */
    u->gen++;
    unsigned tail = *u->sq_tail;
    for (Py_ssize_t k = 0; k < wave; k++) {
      int fd;
      PyObject *chunks;
      if (batch_entry(fast, clfast, done + k, &fd, &chunks) < 0) {
        bad = 1;
        break;
      }
      nchv[k] = acquire_iov(chunks, &bufsv[k], &iovv[k], &fastv[k]);
      if (nchv[k] < 0) {
        bad = 1;
        break;
      }
      msgs[k].msg_iov = iovv[k];
      msgs[k].msg_iovlen = (size_t)nchv[k];
      unsigned slot = tail & *u->sq_mask;
      struct zk_sqe *sqe = &u->sqes[slot];
      memset(sqe, 0, sizeof(*sqe));
      sqe->opcode = ZK_IORING_OP_SENDMSG;
      sqe->fd = fd;
      sqe->addr = (uint64_t)(uintptr_t)&msgs[k];
      sqe->len = 1;
      sqe->msg_flags = MSG_DONTWAIT | MSG_NOSIGNAL;
      sqe->user_data = (u->gen << 20) | (uint64_t)k;
      u->sq_array[slot] = slot;
      tail++;
    }
    if (!bad) {
      __atomic_store_n(u->sq_tail, tail, __ATOMIC_RELEASE);
      /* ONE syscall: submit the whole wave and wait for all of its
       * completions (MSG_DONTWAIT makes every send complete inline,
       * -EAGAIN instead of punting to a poll wait) */
      Py_ssize_t reaped = 0;
      unsigned to_submit = (unsigned)wave;
      int failed_errno = 0;
      while (reaped < wave) {
        int submit_phase = to_submit != 0;
        long r;
        do {
          r = syscall(__NR_io_uring_enter, u->ring_fd, to_submit,
                      (unsigned)(wave - reaped),
                      ZK_IORING_ENTER_GETEVENTS, NULL, 0);
        } while (r < 0 && errno == EINTR);
        enters++;
        if (r < 0) {
          /* a failed SUBMIT enter consumed no SQEs — the caller may
           * safely resend those entries elsewhere; a failed WAIT
           * enter leaves already-submitted sends in flight, so the
           * unfilled slots report EIO ("state unknown": resending
           * could duplicate bytes, the caller must drop) */
          failed_errno = submit_phase ? errno : EIO;
          if (!submit_phase) inflight = 1;
        }
        to_submit = 0;
        /* reap whatever is available — after an enter failure this is
         * the best-effort pass that keeps real completions (and
         * drains them so they cannot leak into the next wave) */
        unsigned head = __atomic_load_n(u->cq_head, __ATOMIC_ACQUIRE);
        unsigned ctail = __atomic_load_n(u->cq_tail, __ATOMIC_ACQUIRE);
        while (head != ctail) {
          struct zk_cqe *cqe = &u->cqarr[head & *u->cq_mask];
          head++;
          if ((cqe->user_data >> 20) != u->gen)
            continue; /* stale generation: consume and ignore */
          Py_ssize_t k = (Py_ssize_t)(cqe->user_data & 0xFFFFF);
          if (k >= 0 && k < wave && !filled[k]) {
            PyObject *val = PyLong_FromLongLong((long long)cqe->res);
            if (val) PyList_SET_ITEM(results, done + k, val);
            filled[k] = 1;
            reaped++;
          }
        }
        __atomic_store_n(u->cq_head, head, __ATOMIC_RELEASE);
        if (failed_errno) {
          /* entries the failed enter never submitted (or whose
           * completions did not arrive) report the errno; slots a
           * real CQE already filled keep their true result */
          long long e = -(long long)failed_errno;
          for (Py_ssize_t k = 0; k < wave; k++) {
            if (filled[k]) continue;
            PyObject *val = PyLong_FromLongLong(e);
            if (val) PyList_SET_ITEM(results, done + k, val);
            filled[k] = 2; /* errno-filled: possibly still in flight */
          }
          break;
        }
      }
    }
    for (Py_ssize_t k = 0; k < wave; k++)
      /* an inflight wave's unreaped entries stay kernel-readable:
       * leak their buffer views (and msgs below) rather than hand
       * the kernel freed memory to send from */
      if (fastv[k] && !(inflight && filled[k] == 2))
        release_iov(bufsv[k], iovv[k], fastv[k], nchv[k]);
    if (!inflight) PyMem_Free(msgs);
    PyMem_Free(bufsv);
    PyMem_Free(iovv);
    PyMem_Free(fastv);
    PyMem_Free(nchv);
    PyMem_Free(filled);
    if (bad) {
      Py_DECREF(fast);
      Py_DECREF(clfast);
      Py_DECREF(results);
      return NULL;
    }
    done += wave;
  }
  Py_DECREF(fast);
  Py_DECREF(clfast);
  return Py_BuildValue("(Nl)", results, enters);
}

/* Batched receive through the ring (io/ingress.py uring arm): one
 * RECVMSG SQE per dirty connection, ONE enter submits and reaps the
 * wave — O(1) syscalls per drain regardless of the dirty-set width.
 * RECVMSG is the stable v5.1 ABI like the send side's SENDMSG; the
 * multishot upgrade (IORING_RECV_MULTISHOT, >= 5.19/6.0 kernels:
 * one standing SQE per connection, completions without resubmission)
 * is declared below and carried until a kernel that has it can
 * measure it — this image's 4.4 kernel gates the whole arm off at
 * probe time anyway. */

#define ZK_IORING_OP_RECVMSG 10
#define ZK_IORING_RECV_MULTISHOT (1u << 1) /* sqe->ioprio flag */

static PyObject *py_uring_recv(PyObject *self, PyObject *args) {
  PyObject *cap, *fds_obj;
  int bufsize;
  if (!PyArg_ParseTuple(args, "OOi", &cap, &fds_obj, &bufsize))
    return NULL;
  if (bufsize <= 0) {
    PyErr_SetString(PyExc_ValueError, "bufsize must be positive");
    return NULL;
  }
  zk_uring *u = uring_from_capsule(cap);
  if (!u) return NULL;
  PyObject *fast = PySequence_Fast(fds_obj, "fds must be a sequence");
  if (!fast) return NULL;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject *results = PyList_New(n);
  if (!results) {
    Py_DECREF(fast);
    return NULL;
  }
  long enters = 0;
  Py_ssize_t done = 0;
  while (done < n) {
    Py_ssize_t wave = n - done;
    if (wave > (Py_ssize_t)u->sq_entries) wave = u->sq_entries;
    struct msghdr *msgs = PyMem_Calloc(wave, sizeof(struct msghdr));
    struct iovec *iov = PyMem_Calloc(wave, sizeof(struct iovec));
    PyObject **bufv = PyMem_Calloc(wave, sizeof(PyObject *));
    char *filled = PyMem_Calloc(wave, 1);
    if (!msgs || !iov || !bufv || !filled) {
      PyMem_Free(msgs);
      PyMem_Free(iov);
      PyMem_Free(bufv);
      PyMem_Free(filled);
      Py_DECREF(fast);
      Py_DECREF(results);
      return PyErr_NoMemory();
    }
    int bad = 0;
    int inflight = 0; /* wait-phase enter failure: submitted recvs may
                       * still complete — their buffers (and the
                       * msghdr/iovec the SQEs point at) belong to the
                       * kernel now and must be LEAKED, never freed,
                       * or a late completion DMA-writes freed heap */
    u->gen++;
    unsigned tail = *u->sq_tail;
    for (Py_ssize_t k = 0; k < wave; k++) {
      long fd = PyLong_AsLong(PySequence_Fast_GET_ITEM(fast, done + k));
      if (fd == -1 && PyErr_Occurred()) {
        bad = 1;
        break;
      }
      bufv[k] = PyBytes_FromStringAndSize(NULL, bufsize);
      if (!bufv[k]) {
        bad = 1;
        break;
      }
      iov[k].iov_base = PyBytes_AS_STRING(bufv[k]);
      iov[k].iov_len = (size_t)bufsize;
      msgs[k].msg_iov = &iov[k];
      msgs[k].msg_iovlen = 1;
      unsigned slot = tail & *u->sq_mask;
      struct zk_sqe *sqe = &u->sqes[slot];
      memset(sqe, 0, sizeof(*sqe));
      sqe->opcode = ZK_IORING_OP_RECVMSG;
      sqe->fd = (int)fd;
      sqe->addr = (uint64_t)(uintptr_t)&msgs[k];
      sqe->len = 1;
      sqe->msg_flags = MSG_DONTWAIT;
      sqe->user_data = (u->gen << 20) | (uint64_t)k;
      u->sq_array[slot] = slot;
      tail++;
    }
    if (!bad) {
      __atomic_store_n(u->sq_tail, tail, __ATOMIC_RELEASE);
      Py_ssize_t reaped = 0;
      unsigned to_submit = (unsigned)wave;
      int failed_errno = 0;
      while (reaped < wave) {
        int submit_phase = to_submit != 0;
        long r;
        do {
          r = syscall(__NR_io_uring_enter, u->ring_fd, to_submit,
                      (unsigned)(wave - reaped),
                      ZK_IORING_ENTER_GETEVENTS, NULL, 0);
        } while (r < 0 && errno == EINTR);
        enters++;
        if (r < 0) {
          /* same contract as uring_submit: a failed SUBMIT enter
           * consumed no SQEs (the caller may retry elsewhere); a
           * failed WAIT enter leaves recvs possibly in flight, so
           * unfilled slots report EIO — their buffers were handed to
           * the kernel and must not be reused */
          failed_errno = submit_phase ? errno : EIO;
          if (!submit_phase) inflight = 1;
        }
        to_submit = 0;
        unsigned head = __atomic_load_n(u->cq_head, __ATOMIC_ACQUIRE);
        unsigned ctail = __atomic_load_n(u->cq_tail, __ATOMIC_ACQUIRE);
        while (head != ctail) {
          struct zk_cqe *cqe = &u->cqarr[head & *u->cq_mask];
          head++;
          if ((cqe->user_data >> 20) != u->gen)
            continue; /* stale generation: consume and ignore */
          Py_ssize_t k = (Py_ssize_t)(cqe->user_data & 0xFFFFF);
          if (k >= 0 && k < wave && !filled[k]) {
            PyObject *val;
            if (cqe->res < 0) {
              val = PyLong_FromLong((long)cqe->res);
              Py_CLEAR(bufv[k]);
            } else {
              val = bufv[k];
              bufv[k] = NULL;
              if (cqe->res < bufsize &&
                  _PyBytes_Resize(&val, cqe->res) < 0) {
                PyErr_Clear();
                val = PyLong_FromLong(-(long)ENOMEM);
              }
            }
            if (val) PyList_SET_ITEM(results, done + k, val);
            filled[k] = 1;
            reaped++;
          }
        }
        __atomic_store_n(u->cq_head, head, __ATOMIC_RELEASE);
        if (failed_errno) {
          long e = -(long)failed_errno;
          for (Py_ssize_t k = 0; k < wave; k++) {
            if (filled[k]) continue;
            PyObject *val = PyLong_FromLong(e);
            if (val) PyList_SET_ITEM(results, done + k, val);
            filled[k] = 1;
          }
          break;
        }
      }
    }
    if (!inflight) {
      /* normal wave: every CQE reaped (or nothing was submitted) —
       * slots still in bufv are ours to drop */
      for (Py_ssize_t k = 0; k < wave; k++) Py_XDECREF(bufv[k]);
      PyMem_Free(msgs);
      PyMem_Free(iov);
    }
    /* inflight: leak bufv[k] objects + msgs/iov (kernel-owned); the
     * bookkeeping arrays below were never handed to the kernel */
    PyMem_Free(bufv);
    PyMem_Free(filled);
    if (bad) {
      Py_DECREF(fast);
      Py_DECREF(results);
      return NULL;
    }
    done += wave;
  }
  Py_DECREF(fast);
  return Py_BuildValue("(Nl)", results, enters);
}

static PyObject *py_uring_close(PyObject *self, PyObject *args) {
  PyObject *cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return NULL;
  zk_uring *u = (zk_uring *)PyCapsule_GetPointer(cap, "zkwire.uring");
  if (!u) return NULL;
  if (u != &uring_closed) {
    /* point the capsule at the sentinel first so the destructor (or
     * a second close) can never double-free */
    if (PyCapsule_SetPointer(cap, &uring_closed) < 0) return NULL;
    uring_free(u);
  }
  Py_RETURN_NONE;
}

#else /* !__linux__ */

static PyObject *py_uring_unsupported(PyObject *self, PyObject *args) {
  errno = ENOSYS;
  return PyErr_SetFromErrno(PyExc_OSError);
}
#define py_uring_create py_uring_unsupported
#define py_uring_submit py_uring_unsupported
#define py_uring_recv py_uring_unsupported
#define py_uring_close py_uring_unsupported

#endif /* __linux__ */

static PyMethodDef methods[] = {
    {"setup", py_setup, METH_VARARGS,
     "setup(Stat, ACL, Id, Perm, CreateFlag, err_names, notif_types, "
     "states, layouts, req_opcodes, op_names, err_codes, notif_codes, "
     "state_codes, op_codes) — see native.ext_setup_args() for the "
     "canonical argument builder"},
    {"decode_responses", py_decode_responses, METH_VARARGS,
     "decode_responses(buf, xid_map, max_packet) -> "
     "(pkts, consumed, err_kind, err_msg)"},
    {"decode_requests", py_decode_requests, METH_VARARGS,
     "decode_requests(buf, max_packet) -> "
     "(pkts, consumed, err_kind, err_msg)"},
    {"encode_request", py_encode_request, METH_VARARGS,
     "encode_request(pkt) -> framed bytes, or None to fall back"},
    {"encode_response", py_encode_response, METH_VARARGS,
     "encode_response(pkt) -> framed bytes, or None to fall back"},
    {"crc32c", py_crc32c, METH_VARARGS,
     "crc32c(data, crc=0) -> CRC32C (Castagnoli) of data, chainable"},
    {"submit_writev", py_submit_writev, METH_VARARGS,
     "submit_writev(fds, chunklists) -> [written|-errno, ...] — one "
     "vectored write per entry, join-free (parallel arrays)"},
    {"uring_create", py_uring_create, METH_VARARGS,
     "uring_create(depth=256) -> capsule (OSError when io_uring is "
     "unavailable)"},
    {"uring_submit", py_uring_submit, METH_VARARGS,
     "uring_submit(ring, fds, chunklists) -> "
     "([sent|-errno, ...], enter_syscalls) — one chained submission "
     "covering the whole batch"},
    {"drain_recv", py_drain_recv, METH_VARARGS,
     "drain_recv(fds, bufsize) -> [bytes|-errno, ...] — one receive "
     "per fd in ONE C call (b'' = EOF; -EAGAIN = nothing pending)"},
    {"uring_recv", py_uring_recv, METH_VARARGS,
     "uring_recv(ring, fds, bufsize) -> "
     "([bytes|-errno, ...], enter_syscalls) — one chained RECVMSG "
     "submission covering the whole dirty set"},
    {"uring_close", py_uring_close, METH_VARARGS,
     "uring_close(ring) — unmap and close the ring fd"},
    {"abi_version", py_abi_version, METH_NOARGS, "native ABI version"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_zkwire_ext",
    "C decoder for the zkstream_tpu receive hot path", -1, methods};

PyMODINIT_FUNC PyInit__zkwire_ext(void) {
  crc32c_table_init();
  s_xid = PyUnicode_InternFromString("xid");
  s_zxid = PyUnicode_InternFromString("zxid");
  s_err = PyUnicode_InternFromString("err");
  s_opcode = PyUnicode_InternFromString("opcode");
  s_data = PyUnicode_InternFromString("data");
  s_stat = PyUnicode_InternFromString("stat");
  s_path = PyUnicode_InternFromString("path");
  s_children = PyUnicode_InternFromString("children");
  s_acl = PyUnicode_InternFromString("acl");
  s_type = PyUnicode_InternFromString("type");
  s_state = PyUnicode_InternFromString("state");
  s_watch = PyUnicode_InternFromString("watch");
  s_version = PyUnicode_InternFromString("version");
  s_relZxid = PyUnicode_InternFromString("relZxid");
  s_events = PyUnicode_InternFromString("events");
  s_flags = PyUnicode_InternFromString("flags");
  s_mode = PyUnicode_InternFromString("mode");
  s_notification = PyUnicode_InternFromString("NOTIFICATION");
  s_ping = PyUnicode_InternFromString("PING");
  s_auth = PyUnicode_InternFromString("AUTH");
  s_set_watches = PyUnicode_InternFromString("SET_WATCHES");
  s_ok = PyUnicode_InternFromString("OK");
  s_dataChanged = PyUnicode_InternFromString("dataChanged");
  s_createdOrDestroyed =
      PyUnicode_InternFromString("createdOrDestroyed");
  s_childrenChanged = PyUnicode_InternFromString("childrenChanged");
  s_persistent = PyUnicode_InternFromString("persistent");
  s_persistentRecursive =
      PyUnicode_InternFromString("persistentRecursive");
  s_results = PyUnicode_InternFromString("results");
  s_op = PyUnicode_InternFromString("op");
  s_ops = PyUnicode_InternFromString("ops");
  s_op_create = PyUnicode_InternFromString("create");
  s_op_delete = PyUnicode_InternFromString("delete");
  s_op_set_data = PyUnicode_InternFromString("set_data");
  s_op_check = PyUnicode_InternFromString("check");
  s_op_error = PyUnicode_InternFromString("error");
  s_perms = PyUnicode_InternFromString("perms");
  s_scheme = PyUnicode_InternFromString("scheme");
  s_id_attr = PyUnicode_InternFromString("id");
  return PyModule_Create(&moduledef);
}
