// zkwire: native host-side data plane for the zkstream_tpu runtime.
//
// The TPU path (ops/pallas_scan.py) handles fleet-scale batched decode;
// this library is its host-side counterpart for the per-connection
// scalar path the asyncio runtime runs on every socket read — the same
// role the reference's per-connection decode loop plays
// (lib/zk-streams.js:39-99 and the drain in lib/connection-fsm.js:
// 213-229), hoisted out of interpreted Python into C++.
//
// Exposed as a plain C ABI consumed via ctypes
// (zkstream_tpu/utils/native.py); no Python.h dependency, so it builds
// with a bare g++ -shared and the Python layer degrades gracefully
// when the library is absent.

#include <cstdint>
#include <cstring>

namespace {

inline int32_t be32(const uint8_t *p) {
  return (int32_t)(((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
                   ((uint32_t)p[2] << 8) | (uint32_t)p[3]);
}

}  // namespace

extern "C" {

// Slice complete length-prefixed frames out of buf[0:len].
//
// Writes up to max_frames (body_start, body_size) pairs.  Returns the
// number of complete frames found, or -1 on an invalid length prefix
// (negative or > max_packet — the BAD_LENGTH condition of
// lib/zk-streams.js:47-53).  *resid receives the cursor after the last
// complete frame (bytes from there to len are a partial frame for the
// caller to keep buffered); on BAD_LENGTH it receives the offending
// frame's prefix offset.
int32_t zkwire_frame_scan(const uint8_t *buf, int32_t len,
                          int32_t max_packet, int32_t max_frames,
                          int32_t *starts, int32_t *sizes,
                          int32_t *resid) {
  int32_t off = 0, n = 0;
  while (n < max_frames && len - off >= 4) {
    int32_t ln = be32(buf + off);
    if (ln < 0 || ln > max_packet) {
      *resid = off;
      return -1;
    }
    if (len - off < 4 + ln) break;
    starts[n] = off + 4;
    sizes[n] = ln;
    ++n;
    off += 4 + ln;
  }
  *resid = off;
  return n;
}

// ABI version tag so the Python loader can reject a stale build.
int32_t zkwire_abi_version(void) { return 1; }

}  // extern "C"
